"""Elastic fleet, part 2 (docs/fault-tolerance.md "Elasticity"):
runtime server scale-up join, graceful drain, gray-failure eviction,
and the sensor-driven autoscaler loop.

Protocol-level pieces (rebalance plans, the autoscaler controller) test
pure and in-process; the join/drain/eviction drills run against real
in-process native servers (the chaos knobs are read per Server
instance, so a slow straggler and a healthy peer coexist in one test
process). The heavier partial-reply-window subprocess drill lives in
test_chaos.py next to the other churn tests.
"""

import os
import threading
import time

import numpy as np
import pytest

from byteps_tpu.config import Config
from byteps_tpu.core.autoscaler import (
    AutoscaleController, AutoscalerPlane, Decision, FleetSample,
)
from byteps_tpu.core.registry import TensorRegistry
from byteps_tpu.core.types import DataType

_PORT = [28300]


def _registry(num_servers, partition_bytes=4096):
    return TensorRegistry(Config(num_workers=1, num_servers=num_servers,
                                 partition_bytes=partition_bytes))


# --------------------------------------------------------------------- #
# registry: the version-fenced rebalance plan engine
# --------------------------------------------------------------------- #


def test_plan_join_moves_fair_share_to_newcomer():
    reg = _registry(2)
    for i in range(8):
        reg.init_tensor(f"j{i}", 3 * 4096, DataType.FLOAT32)
    total = sum(reg.server_loads())
    new = reg.add_server()
    assert new == 2
    plan = reg.plan_join(new)
    assert plan.kind == "join" and plan.server == 2
    v0 = reg.routing_version
    moved = reg.rebalance(plan)
    assert moved == plan.keys()
    assert reg.routing_version == v0 + 1
    loads = reg.server_loads()
    assert sum(loads) == total  # bytes conserved, just re-homed
    # the newcomer holds roughly its fair share (within one partition)
    assert loads[2] >= total // 3 - 3 * 4096
    assert loads[2] > 0
    # moved partitions actually point at the newcomer
    moved_set = set(moved)
    for ctx in reg.contexts_in_order():
        for p in ctx.partitions:
            if p.key in moved_set:
                assert p.server == 2


def test_plan_join_is_deterministic_across_workers():
    """Two independent registries with the same declaration history
    must compute the identical join plan — workers re-route with no
    coordination message, exactly like crash migration."""
    regs = [_registry(2) for _ in range(2)]
    for reg in regs:
        for i in range(6):
            reg.init_tensor(f"d{i}", 2 * 4096, DataType.FLOAT32)
        reg.add_server()
    plans = [reg.plan_join(2) for reg in regs]
    assert plans[0] == plans[1]
    for reg, plan in zip(regs, plans):
        reg.rebalance(plan)
    tables = [[(p.key, p.server)
               for ctx in reg.contexts_in_order()
               for p in ctx.partitions] for reg in regs]
    assert tables[0] == tables[1]


def test_rebalance_rejects_stale_plan():
    reg = _registry(2)
    reg.init_tensor("x", 4 * 4096, DataType.FLOAT32)
    new = reg.add_server()
    plan = reg.plan_join(new)
    reg.migrate_server(0)  # routing changed under the plan
    with pytest.raises(RuntimeError, match="stale rebalance plan"):
        reg.rebalance(plan)


def test_plan_drain_is_migrate_with_retirement():
    """Drain and crash migration are ONE code path: the drain plan's
    moves match what migrate_server would do, plus retirement."""
    reg_a = _registry(3)
    reg_b = _registry(3)
    for reg in (reg_a, reg_b):
        for i in range(5):
            reg.init_tensor(f"m{i}", 2 * 4096, DataType.FLOAT32)
    plan = reg_a.plan_drain(1)
    assert plan.retire and plan.kind == "drain"
    moved_a = reg_a.rebalance(plan)
    moved_b = reg_b.migrate_server(1)
    assert moved_a == moved_b  # same keys, same engine
    tables = [[(p.key, p.server) for ctx in r.contexts_in_order()
               for p in ctx.partitions] for r in (reg_a, reg_b)]
    assert tables[0] == tables[1]  # same destinations too
    assert reg_a.dead_servers() == [1]
    assert reg_a.server_loads()[1] == 0
    # a drained server is out of NEW assignments too
    ctx = reg_a.init_tensor("post", 8 * 4096, DataType.FLOAT32)
    assert all(p.server != 1 for p in ctx.partitions)


def test_plan_drain_last_survivor_raises():
    reg = _registry(2)
    reg.init_tensor("x", 4096, DataType.FLOAT32)
    reg.migrate_server(0)
    with pytest.raises(RuntimeError, match="no other surviving"):
        reg.plan_drain(1)


def test_redeclare_bumps_routing_version():
    reg = _registry(2)
    reg.init_tensor("x", 4 * 4096, DataType.FLOAT32)
    v0 = reg.routing_version
    reg.redeclare_all(Config(num_workers=1, num_servers=1,
                             partition_bytes=4096))
    assert reg.routing_version == v0 + 1
    for ctx in reg.contexts_in_order():
        assert all(p.server == 0 for p in ctx.partitions)


# --------------------------------------------------------------------- #
# autoscaler controller: pure, deterministic, hysteresis
# --------------------------------------------------------------------- #


def _pull_bound(step, alive=1, per_server=None):
    return FleetSample(step=step, compute_ms=10.0, pull_ms=40.0,
                       per_server=per_server or {}, num_alive=alive)


def _idle(step, alive=2, per_server=None):
    return FleetSample(step=step, compute_ms=10.0, pull_ms=1.0,
                       per_server=per_server or {}, num_alive=alive)


def _balanced(step, alive=2, per_server=None):
    return FleetSample(step=step, compute_ms=10.0, pull_ms=10.0,
                       per_server=per_server or {}, num_alive=alive)


def test_controller_add_after_hysteresis():
    c = AutoscaleController(up_steps=3, cooldown=5)
    ds = [c.observe(_pull_bound(s)) for s in range(1, 4)]
    assert [d.action for d in ds] == ["hold", "hold", "add"]
    # cooldown: even under continued pressure, no immediate second add
    ds = [c.observe(_pull_bound(s, alive=2)) for s in range(4, 9)]
    assert all(d.action == "hold" for d in ds)


def test_controller_drain_after_idle_streak():
    c = AutoscaleController(down_steps=4, cooldown=2)
    ds = [c.observe(_idle(s)) for s in range(1, 5)]
    assert [d.action for d in ds] == ["hold", "hold", "hold", "drain"]
    # never drain below min_servers
    c2 = AutoscaleController(down_steps=2, min_servers=1)
    ds = [c2.observe(_idle(s, alive=1)) for s in range(1, 6)]
    assert all(d.action == "hold" for d in ds)


def test_controller_never_flaps_under_thresholds():
    """Signals inside the hysteresis band (neither pull-bound by the
    ratio nor idle) must never produce a decision, however long the
    run."""
    c = AutoscaleController()
    for s in range(1, 200):
        assert c.observe(_balanced(s)).action == "hold"


def test_controller_evicts_the_straggler():
    c = AutoscaleController(evict_factor=4.0, evict_steps=3)
    sig = {0: 2.0, 1: 2.2, 2: 50.0}  # server 2: 25x the median
    ds = [c.observe(_balanced(s, alive=3, per_server=sig))
          for s in range(1, 4)]
    assert [d.action for d in ds] == ["hold", "hold", "evict"]
    assert ds[-1].server == 2
    # an interrupted streak resets: 2 bad steps, 1 good, 2 bad -> hold
    c2 = AutoscaleController(evict_factor=4.0, evict_steps=3)
    seq = [sig, sig, {0: 2.0, 1: 2.2, 2: 2.1}, sig, sig]
    ds = [c2.observe(_balanced(s + 1, alive=3, per_server=ps))
          for s, ps in enumerate(seq)]
    assert all(d.action == "hold" for d in ds)


def test_controller_evict_noise_floor():
    """Sub-millisecond deltas on an idle fleet are measurement noise,
    not gray failure — even at a huge ratio over the median."""
    c = AutoscaleController(evict_factor=2.0, evict_steps=1)
    sig = {0: 0.001, 1: 0.0005, 2: 0.9}
    for s in range(1, 10):
        assert c.observe(
            _balanced(s, alive=3, per_server=sig)).action == "hold"


def test_controller_two_stack_determinism():
    """THE aggregation-safety property (acceptance): two independent
    controller stacks fed the identical signal sequence emit the
    identical decision sequence — same shape as the codec-plane
    two-stack test."""
    def sequence():
        out = []
        for s in range(1, 40):
            if s % 7 < 3:
                out.append(_pull_bound(s, alive=2,
                                       per_server={0: 3.0, 1: 3.3}))
            elif s % 7 < 5:
                out.append(_idle(s, alive=2,
                                 per_server={0: 2.0, 1: 40.0}))
            else:
                out.append(_balanced(s, alive=2,
                                     per_server={0: 2.0, 1: 40.0}))
        return out

    stacks = [AutoscaleController(up_steps=2, down_steps=3,
                                  evict_factor=4.0, evict_steps=2,
                                  cooldown=4) for _ in range(2)]
    decisions = [[c.observe(s) for s in sequence()] for c in stacks]
    assert decisions[0] == decisions[1]
    # and the sequence actually contains non-hold decisions (the test
    # must not pass vacuously on an all-hold run)
    assert any(not d.hold for d in decisions[0])


def test_straggler_signal_is_per_request_not_per_load():
    """Load imbalance must never read as gray failure: a healthy
    server handling 10x the requests (10x the ABSOLUTE stage time,
    equal per-request latency) gets signal ≈ its peers'; a true
    straggler (same request count, 50x the time) stands out."""
    plane = AutoscalerPlane.__new__(AutoscalerPlane)
    plane._mu = threading.Lock()
    plane._base = {}

    def sweep(values):
        plane._sweep_per_server = lambda: {
            s: {"queue_ns": q, "reply_ns": r, "queue_count": n}
            for s, (q, r, n) in values.items()}
        return plane._straggler_signal()

    # baseline tick: first sighting contributes NO signal (cumulative-
    # since-boot counters are not a step delta)
    assert sweep({0: (10**9, 10**9, 100), 1: (10**9, 10**9, 100)}) == {}
    # busy-but-healthy: server 0 does 10x the requests at the same
    # 2ms/request latency -> signals within noise of each other
    sig = sweep({0: (10**9 + 100 * 10 ** 6, 10**9 + 100 * 10**6, 200),
                 1: (10**9 + 10 * 10 ** 6, 10**9 + 10 * 10**6, 110)})
    assert abs(sig[0] - sig[1]) < 0.01, sig
    # true straggler: same request count, 50x the per-request time
    sig = sweep({0: (10**9 + 300 * 10**6, 10**9 + 300 * 10**6, 300),
                 1: (10**9 + 1010 * 10**6, 10**9 + 1010 * 10**6, 120)})
    assert sig[1] > 20 * sig[0], sig
    # a server that served nothing this window has no latency evidence
    sig = sweep({0: (10**9 + 400 * 10**6, 10**9 + 400 * 10**6, 350),
                 1: (10**9 + 1010 * 10**6, 10**9 + 1010 * 10**6, 120)})
    assert sig[1] == 0.0


def test_retirement_survives_resume_crash_verdicts_do_not():
    """A drained/evicted slot (config.retired_servers, the env
    round-trip) stays masked through redeclare_all; a crash verdict
    resets — a restarted server may re-use its index."""
    reg = TensorRegistry(Config(num_workers=1, num_servers=3,
                                partition_bytes=4096))
    for i in range(4):
        reg.init_tensor(f"rr{i}", 2 * 4096, DataType.FLOAT32)
    reg.migrate_server(1)  # crash verdict
    assert reg.dead_servers() == [1]
    # resume with index 2 RETIRED (drained earlier, env-carried)
    reg.redeclare_all(Config(num_workers=1, num_servers=3,
                             partition_bytes=4096,
                             retired_servers=(2,)))
    assert reg.dead_servers() == [2]  # crash reset, retirement kept
    for ctx in reg.contexts_in_order():
        for p in ctx.partitions:
            assert p.server != 2


def test_decision_is_frozen_value():
    d = Decision(1, "evict", 2, "r")
    with pytest.raises(Exception):
        d.action = "hold"


# --------------------------------------------------------------------- #
# live fleet drills: join / drain / gray-failure eviction
# --------------------------------------------------------------------- #


def _start_server(port, num_workers=1, env=None):
    """In-process server thread; chaos/throttle knobs are read per
    Server instance at construction, so a scoped env mutation taints
    exactly one server. When ``env`` is given, the restore waits for
    the port to ACCEPT — the Server (and its Chaos) constructs before
    it binds, so an accepting port proves the knobs were read (a fixed
    sleep raced thread-start latency under full-suite load)."""
    from byteps_tpu.server import run_server

    prior = {}
    if env:
        prior = {k: os.environ.get(k) for k in env}
        os.environ.update(env)
    try:
        t = threading.Thread(
            target=run_server,
            args=(port, Config(num_workers=num_workers, num_servers=1)),
            daemon=True)
        t.start()
        if env:
            _wait_port(port)
    finally:
        for k, v in prior.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    return t


def _wait_port(port, timeout=60):
    from byteps_tpu.utils.net import wait_port

    wait_port(port, timeout)


def _ports(n):
    from byteps_tpu.utils.net import free_port

    ports = []
    while len(ports) < n:
        p = free_port()
        if p not in ports:
            ports.append(p)
    return ports


class _Fleet:
    """Scoped loopback fleet: N in-process servers + an initialized bps
    worker, with env save/restore (the test-side twin of bench.py's
    _loopback_ps, plus runtime growth)."""

    def __init__(self, num_servers, extra_env=None):
        self.ports = _ports(num_servers)
        self.threads = []
        self.env = {
            "DMLC_NUM_WORKER": "1",
            "DMLC_NUM_SERVER": str(num_servers),
            "DMLC_PS_ROOT_URI": "127.0.0.1",
            "DMLC_PS_ROOT_PORT": str(self.ports[0]),
            "BYTEPS_SERVER_HOSTS": ",".join(
                f"127.0.0.1:{p}" for p in self.ports),
            "BYTEPS_FORCE_DISTRIBUTED": "1",
            # drain/evict exports this; scope it so a draining test
            # never leaks retirements into the rest of the suite
            "BYTEPS_RETIRED_SERVERS": "",
            **(extra_env or {}),
        }
        self.prior = {k: os.environ.get(k) for k in self.env}

    def __enter__(self):
        from byteps_tpu.core.state import GlobalState

        os.environ.update(self.env)
        for p in self.ports:
            self.threads.append(_start_server(p))
        for p in self.ports:
            _wait_port(p)
        GlobalState._instance = None
        import byteps_tpu as bps
        bps.init()
        self.bps = bps
        return bps

    def grow(self, env=None):
        """Start ONE more in-process server (runtime scale-up target);
        returns its address."""
        port = _ports(1)[0]
        self.threads.append(_start_server(port, env=env))
        _wait_port(port)
        self.ports.append(port)
        return f"127.0.0.1:{port}"

    def __exit__(self, *exc):
        from byteps_tpu.core.state import GlobalState

        try:
            self.bps.shutdown()
        except Exception:  # noqa: BLE001 - teardown best-effort
            pass
        GlobalState._instance = None
        for t in self.threads:
            t.join(timeout=20)
        for k, v in self.prior.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def _rounds(bps, grads, lo, hi, prefix="el"):
    for r in range(lo, hi):
        hs = [bps.push_pull_async(g * (r + 1), f"{prefix}{i}",
                                  average=False)
              for i, g in enumerate(grads)]
        for g, h in zip(grads, hs):
            out = np.array(bps.synchronize(h, timeout=120))
            np.testing.assert_array_equal(out, g * (r + 1))


@pytest.mark.chaos
def test_join_then_drain_roundtrip_bitwise(tmp_path):
    """Scale up then scale back down, live: a runtime-started server
    joins (version-fenced rebalance moves keys TO it), training
    continues bitwise; a drain moves them back out and retires it,
    training still bitwise. Counters + flight events pin the
    lifecycle."""
    from byteps_tpu.core import flight as flight_mod
    from byteps_tpu.core.state import get_state

    fleet = _Fleet(1)
    with fleet as bps:
        state = get_state()
        rng = np.random.RandomState(3)
        grads = [rng.randn(2048).astype(np.float32) for _ in range(6)]
        _rounds(bps, grads, 0, 2)

        idx = bps.add_server(fleet.grow())
        assert idx == 1
        v_join = state.registry.routing_version
        loads = state.registry.server_loads()
        assert loads[1] > 0, "join moved no keys to the newcomer"
        _rounds(bps, grads, 2, 5)

        moved = bps.drain_server(1)
        assert moved, "drain moved nothing back"
        assert state.registry.dead_servers() == [1]
        assert state.registry.server_loads()[1] == 0
        assert state.registry.routing_version > v_join
        _rounds(bps, grads, 5, 7)

        snap = bps.get_metrics()
        assert snap["counters"]["registry/joins"] == 1
        assert snap["counters"]["registry/drains"] == 1
        assert snap["counters"]["server/evictions"] == 0
        # the drained server latched its advisory flag (DRAIN_REQ ACK)
        fleet_snap = bps.get_fleet_metrics()["fleet"]
        assert fleet_snap["server"]["1"]["draining"] >= 1
        # flight: join precedes drain precedes the per-key migrations
        evs = flight_mod.get_recorder().events()
        kinds = [e["kind"] for e in evs]
        assert "server_join" in kinds and "server_drain" in kinds
        assert kinds.index("server_join") < kinds.index("server_drain")
        mig = [i for i, k in enumerate(kinds) if k == "key_migration"]
        assert mig and min(mig) > kinds.index("server_drain")
        # drain does NOT terminate the server process; fleet teardown's
        # SHUTDOWN (sent to every connected server) releases it


@pytest.mark.chaos
def test_gray_failure_eviction_drill(tmp_path):
    """THE acceptance drill: under BYTEPS_CHAOS_SLOW_SERVER the
    deterministic detector evicts the straggler within the pinned step
    budget, training completes with bitwise parity, and the flight
    record shows the detect -> drain(evict) -> migrate chain in causal
    order."""
    from byteps_tpu.core import flight as flight_mod
    from byteps_tpu.core.state import get_state

    evict_steps = 3
    fleet = _Fleet(1, extra_env={
        "BYTEPS_AUTOSCALE": "act",
        "BYTEPS_AUTOSCALE_EVICT_STEPS": str(evict_steps),
        "BYTEPS_AUTOSCALE_EVICT_FACTOR": "4",
        "BYTEPS_FLIGHT_DIR": str(tmp_path / "flight")})
    with fleet as bps:
        state = get_state()
        plane = bps.get_autoscaler()
        assert plane is not None
        rng = np.random.RandomState(9)
        grads = [rng.randn(2048).astype(np.float32) for _ in range(6)]
        _rounds(bps, grads, 0, 1, prefix="gray")  # declare + init
        # the straggler joins at runtime with a persistent 40ms/request
        # injected delay (read per Server instance — the healthy server
        # is untouched); the join rebalance hands it real keys
        bps.add_server(
            fleet.grow(env={"BYTEPS_CHAOS_SLOW_SERVER": "40"}))
        assert state.registry.server_loads()[1] > 0

        evicted_at = None
        budget = evict_steps + 4  # pinned step budget for detection
        for r in range(budget):
            _rounds(bps, grads, r, r + 1, prefix="gray")
            d = plane.tick()  # the step-boundary sensor tick
            if d.action == "evict":
                evicted_at = r
                break
        assert evicted_at is not None, (
            f"detector did not evict within {budget} steps: "
            f"{plane.decisions()}")
        assert evicted_at <= budget - 1
        # the straggler is gone from the routing table; training
        # completes bitwise on the survivor
        assert state.registry.dead_servers() == [1]
        assert state.registry.server_loads()[1] == 0
        _rounds(bps, grads, budget, budget + 2, prefix="gray")

        snap = bps.get_metrics()
        assert snap["counters"]["server/evictions"] == 1
        assert snap["counters"]["registry/drains"] == 1
        assert snap["counters"]["autoscale/decisions"] >= 1
        assert snap["autoscale"]["last"]["action"] == "evict"
        assert snap["autoscale"]["last"]["server"] == 1

        # flight record: detect -> evict(drain) -> per-key migration,
        # causally ordered in one timeline (satellite: the chaos-suite
        # assertion pinning detect→drain→migrate order)
        evs = flight_mod.get_recorder().events()
        kinds = [e["kind"] for e in evs]
        assert "autoscale_decision" in kinds
        assert "server_evict" in kinds
        i_detect = kinds.index("autoscale_decision")
        i_evict = kinds.index("server_evict")
        mig = [i for i, k in enumerate(kinds) if k == "key_migration"]
        assert i_detect < i_evict, "evict recorded before its decision"
        assert mig and min(mig) > i_evict, \
            "migration recorded before the evict"
        ts = [e["ts_ns"] for e in evs]
        assert ts == sorted(ts), "flight events out of causal order"
        ev = evs[i_evict]
        assert ev["key"] == 1  # the evict names the straggler
        # and the merged dump (worker + servers) stays causally sorted
        import json
        dump_path = bps.dump_flight_record(
            str(tmp_path / "gray-flight.json"))
        assert dump_path and os.path.exists(dump_path)
        with open(dump_path) as f:
            doc = json.load(f)
        merged_ts = [e["ts_ns"] for e in doc["merged"]]
        assert merged_ts == sorted(merged_ts)


@pytest.mark.chaos
def test_resume_with_different_num_servers_rebuilds_routing():
    """Satellite: bps.resume with a DIFFERENT num_servers must rebuild
    routing against the new topology (never a stale assignment table),
    with bitwise parity across the suspend/resume cycle."""
    from byteps_tpu.core.state import get_state
    from byteps_tpu.server.client import PSClient

    fleet = _Fleet(2)
    with fleet as bps:
        state = get_state()
        rng = np.random.RandomState(17)
        grads = [rng.randn(4096).astype(np.float32) for _ in range(6)]
        _rounds(bps, grads, 0, 2, prefix="rs")
        owners = {p.server for ctx in state.registry.contexts_in_order()
                  for p in ctx.partitions}
        assert owners == {0, 1}, f"keys not spread: {owners}"
        v0 = state.registry.routing_version

        bps.suspend()
        bps.resume(num_workers=1, num_servers=1)
        state = get_state()
        assert state.config.num_servers == 1
        # the WHOLE table was rebuilt: no partition may still target
        # the departed server, and the fence advanced
        for ctx in state.registry.contexts_in_order():
            for p in ctx.partitions:
                assert p.server == 0
        assert state.registry.routing_version > v0
        assert state.registry.dead_servers() == []
        # bitwise parity across the cycle (1 worker: aggregate == push)
        _rounds(bps, grads, 2, 4, prefix="rs")

        # resume trimmed the host list to the new count
        assert os.environ["BYTEPS_SERVER_HOSTS"].count(",") == 0

        # growing past the known host list must be a CLEAR error, not a
        # stale-table reconnect
        bps.suspend()
        with pytest.raises(ValueError, match="names only 1"):
            bps.resume(num_workers=1, num_servers=2)
        bps.resume(num_workers=1, num_servers=1)

        # release the abandoned server-1 thread: the resumed 1-server
        # client will never send it the SHUTDOWN it waits for
        PSClient([f"127.0.0.1:{fleet.ports[1]}"], worker_id=0).close()


def test_join_probe_validates_worker_count():
    """A newcomer running a different num_workers must be refused at
    the handshake — routing keys to it would wedge every round. The
    refused index is RETIRED, not leaked: the native conn table cannot
    shrink, so the slot is accounted for and a LATER (correct) join
    still aligns instead of wedging on a table mismatch."""
    from byteps_tpu.core.state import get_state
    from byteps_tpu.server.client import PSClient

    fleet = _Fleet(1)
    with fleet as bps:
        state = get_state()
        port = _ports(1)[0]
        _start_server(port, num_workers=2)  # fleet runs 1
        _wait_port(port)
        rng = np.random.RandomState(4)
        grads = [rng.randn(1024).astype(np.float32) for _ in range(4)]
        _rounds(bps, grads, 0, 1, prefix="jp")
        with pytest.raises(RuntimeError, match="num_workers"):
            bps.add_server(f"127.0.0.1:{port}")
        # the refused slot is retired unused: registry/config cover it
        # (matching the un-shrinkable native table) but nothing ever
        # routes there
        assert state.config.num_servers == 2
        assert state.registry.dead_servers() == [1]
        assert state.registry.server_loads()[1] == 0
        # a subsequent CORRECT join realigns at the next index and
        # works — the one-bad-probe wedge the rollback exists for
        idx = bps.add_server(fleet.grow())
        assert idx == 2
        assert state.registry.server_loads()[2] > 0
        _rounds(bps, grads, 1, 3, prefix="jp")
        # release the 2-worker server: it needs a second SHUTDOWN on
        # top of the one fleet teardown's client will send it
        PSClient([f"127.0.0.1:{port}"], worker_id=1).close()


def test_observer_wiring_drives_autoscaler_tick():
    """StepProfiler.add_observer delivers each finished report on the
    train thread — the autoscaler's sensor tap."""
    from byteps_tpu.core.metrics import StepProfiler

    seen = []
    prof = StepProfiler(window=4)
    prof.add_observer(seen.append)
    b = prof.begin_step()
    r = prof.end_step(b)
    assert seen == [r]
    # a raising observer must not kill the step
    prof.add_observer(lambda _r: (_ for _ in ()).throw(RuntimeError()))
    b = prof.begin_step()
    r2 = prof.end_step(b)
    assert r2 is not None and seen[-1] is r2
