"""On-device compression for the PS path (jax/device_compression.py).

SURVEY §7's "the D2H moves *compressed* bytes" promise: the codec stack
runs inside XLA, the scheduler receives wire-sized payloads, and the
pull reply is decompressed on device. These tests pin (a) wire-format
parity with the host/numpy tier (the C++ server must not be able to
tell the tiers apart), (b) the transfer-size claim itself, and (c) end
to end training through the loopback server."""

import threading

import numpy as np
import pytest

from byteps_tpu.config import Config
from byteps_tpu.ops.compression import host
from byteps_tpu.server import run_server

_PORT = [23900]


def _golden_aggregate(kwargs, xs, n):
    payloads = []
    for x in xs:
        c = host.make_host_codec(kwargs, n)
        payloads.append(c.compress(x, step=0))
    dec = host.make_host_codec(kwargs, n)
    s = sum(dec.decompress(np.frombuffer(p, np.uint8)) for p in payloads)
    wire = host.make_host_codec(kwargs, n).compress(s, step=0)
    return dec.decompress(np.frombuffer(wire, np.uint8))


@pytest.mark.parametrize("kw", [
    {"compressor": "onebit"},
    {"compressor": "topk", "k": "16"},
    {"compressor": "randomk", "k": "16", "seed": "3"},
    {"compressor": "dithering", "s": "32", "seed": "9"},
])
def test_wire_serialization_matches_host_codec(kw):
    """payload_to_wire(jnp payload) must be byte-compatible with the
    host codec's wire (scalar scale/norm may differ by an ulp; all
    index/level/bit lanes must be identical)."""
    import jax.numpy as jnp

    from byteps_tpu.jax.device_compression import (
        _portable, payload_to_wire, wire_to_payload,
    )
    from byteps_tpu.ops.compression import make_compressor

    n = 300
    x = np.random.RandomState(7).randn(n).astype(np.float32)
    codec = _portable(make_compressor(kw, n).codec)
    payload = codec.compress(jnp.asarray(x), step=4)
    wire = payload_to_wire(codec,
                           {k: np.asarray(v) for k, v in payload.items()})
    hwire = np.frombuffer(
        host.make_host_codec(kw, n).compress(x, step=4), np.uint8)
    assert wire.nbytes == hwire.nbytes == \
        host.make_host_codec(kw, n).wire_bytes()
    # scalar tail (scale/norm) may differ by an ulp between np and jnp
    # reductions; everything else must be bit-identical
    body = slice(None)
    if kw["compressor"] in ("onebit", "dithering"):
        body = slice(0, wire.nbytes - 4)
        np.testing.assert_allclose(
            wire[-4:].copy().view(np.float32),
            hwire[-4:].copy().view(np.float32), rtol=1e-6)
    np.testing.assert_array_equal(wire[body], hwire[body])
    # parse -> device decompress must equal the host decompress
    parsed = wire_to_payload(codec, n, wire)
    dev = np.asarray(codec.decompress(
        {k: jnp.asarray(v) for k, v in parsed.items()}))
    hostd = host.make_host_codec(kw, n).decompress(hwire)
    np.testing.assert_allclose(dev, hostd, rtol=1e-6)


def _with_ps(monkeypatch, body, **cfgkw):
    from byteps_tpu.core.state import GlobalState

    port = _PORT[0]
    _PORT[0] += 1
    monkeypatch.setenv("DMLC_NUM_WORKER", "1")
    monkeypatch.setenv("DMLC_NUM_SERVER", "1")
    monkeypatch.setenv("DMLC_PS_ROOT_URI", "127.0.0.1")
    monkeypatch.setenv("DMLC_PS_ROOT_PORT", str(port))
    monkeypatch.setenv("BYTEPS_FORCE_DISTRIBUTED", "1")
    server = threading.Thread(
        target=run_server,
        args=(port, Config(num_workers=1, num_servers=1, **cfgkw)),
        daemon=True)
    server.start()
    GlobalState._instance = None
    import byteps_tpu as bps
    bps.init()
    try:
        from byteps_tpu.core.state import get_state
        body(bps, get_state())
    finally:
        bps.shutdown()
        server.join(timeout=10)
        GlobalState._instance = None


@pytest.mark.parametrize("kw", [
    {"compressor": "onebit"},
    {"compressor": "randomk", "k": "32", "seed": "5"},
])
def test_device_roundtrip_matches_golden(monkeypatch, kw):
    """DeviceCompressor through the real scheduler + C++ server equals
    the host-tier golden aggregate."""
    import jax.numpy as jnp

    from byteps_tpu.jax.device_compression import DeviceCompressor

    n = 4096

    def body(bps, state):
        dc = DeviceCompressor(state.ps_client, 1, kw)
        rng = np.random.RandomState(0)
        x = rng.randn(n).astype(np.float32)
        out = dc.push_pull_leaves(state, ["dt"], [jnp.asarray(x)],
                                  average=False)[0]
        want = _golden_aggregate(kw, [x], n)
        np.testing.assert_allclose(np.asarray(out), want, rtol=1e-6)
        # second round advances the per-tensor round counter (stateful
        # codecs + the server's sync completed_rounds)
        out1 = dc.push_pull_leaves(state, ["dt"], [jnp.asarray(x)],
                                   average=False)[0]
        assert dc._plans["dt"].step == 2
        if kw["compressor"] == "randomk":
            # different rounds draw different indices
            assert not np.array_equal(np.asarray(out), np.asarray(out1))

    _with_ps(monkeypatch, body)


def test_d2h_payload_is_wire_sized(monkeypatch):
    """The round-2 gap (VERDICT weak #2): the device->host hop must carry
    ~wire_bytes(), not dense f32. Asserts the jitted compress output's
    total nbytes is the wire size (1/32 of dense for onebit bits +
    4 scale bytes per partition)."""
    import jax.numpy as jnp

    from byteps_tpu.jax.device_compression import DeviceCompressor

    n = 1 << 20  # 4 MB dense

    def body(bps, state):
        dc = DeviceCompressor(state.ps_client, 1, {"compressor": "onebit"})
        plan = dc.plan(state, "big", n)
        compress_fn, _decompress_fn, spec = dc._get_fns([plan], True)
        packed, _states = compress_fn(
            [jnp.ones(n, jnp.float32)], [plan.states], jnp.int32(0))
        # the D2H hop is now 1-2 dtype-bucketed buffers (not one array
        # per partition payload) and their total is exactly wire-sized
        assert len(packed) <= 2, list(packed)
        total = sum(np.asarray(v).nbytes for v in packed.values())
        dense = n * 4
        assert total == plan.wire_bytes(), (total, plan.wire_bytes())
        assert total < dense / 25, (total, dense)
        # host views must reassemble into the per-partition wire layout
        payloads = spec.unpack_np({k: np.asarray(v)
                                   for k, v in packed.items()})
        assert len(payloads[0]) == len(plan.ctx.partitions)
        assert set(payloads[0][0]) == {"bits", "scale"}

    _with_ps(monkeypatch, body)


def test_device_compressed_training_and_elastic(monkeypatch):
    """make_ps_train_step default path is now device compression: loss
    decreases, EF state lives on device, and suspend/resume re-keys the
    device compressor to the new client."""
    import jax
    import jax.numpy as jnp
    import optax

    from byteps_tpu.jax.train import make_ps_train_step
    from byteps_tpu.models import mlp

    def body(bps, state):
        cfg = mlp.MLPConfig(in_dim=8, hidden=(16,), n_classes=4)
        params = mlp.init_params(jax.random.PRNGKey(0), cfg)
        tx = optax.sgd(0.1)
        opt = tx.init(params)
        rng = np.random.RandomState(0)
        x = jnp.asarray(rng.randn(32, 8), jnp.float32)
        y = jnp.asarray(rng.randint(0, 4, 32), jnp.int32)
        step = make_ps_train_step(
            lambda p, b: mlp.loss_fn(p, b, cfg), tx, state.mesh,
            compression={"compressor": "onebit", "ef": "vanilla"},
            min_compress_bytes=0)
        losses = []
        for _ in range(25):
            params, opt, loss = step(params, opt, {"x": x, "y": y})
            losses.append(float(loss))
        assert losses[-1] < losses[0] * 0.8, losses
        bps.suspend()
        bps.resume(num_workers=1, num_servers=1)
        params, opt, loss = step(params, opt, {"x": x, "y": y})
        assert float(loss) < losses[0]

    _with_ps(monkeypatch, body)


def test_device_vs_host_tier_parity(monkeypatch):
    """Same gradient, same server: the device tier and the host tier must
    produce the same aggregate (the server cannot tell them apart)."""
    import jax.numpy as jnp

    from byteps_tpu.jax.device_compression import DeviceCompressor
    from byteps_tpu.server.compressed import CompressedRegistry

    n = 2048
    kw = {"compressor": "randomk", "k": "64", "seed": "11"}

    def body(bps, state):
        rng = np.random.RandomState(3)
        x = rng.randn(n).astype(np.float32)
        dc = DeviceCompressor(state.ps_client, 1, kw)
        dev = np.asarray(dc.push_pull_leaves(
            state, ["p"], [jnp.asarray(x)], average=False)[0])
        reg = CompressedRegistry(state.ps_client, 1, kw)
        hostout = reg.push_pull(state, "q", x, average=False)
        # both ran round 0 of their own tensors with the same seed ->
        # identical indices, identical values, bit-identical result
        np.testing.assert_array_equal(dev, hostout)

    _with_ps(monkeypatch, body)


def test_zero_size_leaf_passes_through(monkeypatch):
    """A pytree with a 0-element leaf (e.g. an optional bias of shape
    (0,)) must not crash the device-compressed round: zero-size leaves
    carry no data and pass through unchanged while the rest of the tree
    still aggregates (round-4 review regression)."""
    import jax.numpy as jnp

    from byteps_tpu.jax.device_compression import DeviceCompressor

    def body(bps, state):
        dc = DeviceCompressor(state.ps_client, 1,
                              {"compressor": "onebit"})
        lf = jnp.asarray(np.random.RandomState(0).randn(512), jnp.float32)
        empty = jnp.zeros((0,), jnp.float32)
        out = dc.push_pull_leaves(state, ["zlive", "zempty"],
                                  [lf, empty], average=False)
        assert out[1].shape == (0,)
        # the live leaf still went through the codec (onebit: sign*scale)
        assert np.asarray(out[0]).shape == (512,)
        assert np.sign(np.asarray(out[0])).tolist() == \
            np.sign(np.asarray(lf)).tolist()

    _with_ps(monkeypatch, body)
