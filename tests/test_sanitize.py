"""Sanitizer tier for the native PS (SURVEY.md §5.2: the reference ships
no race detection; this build adds it).

Builds ps.cc under -fsanitize=thread and runs a concurrent loopback stress
(two clients hammering overlapping keys: dense, compressed, parked pulls,
barrier) in a subprocess with the TSAN runtime preloaded. Any data race
makes TSAN print a WARNING and exit nonzero (halt_on_error)."""

import os
import subprocess
import sys

import pytest

_STRESS = r"""
import threading, numpy as np
import os, sys
sys.path.insert(0, os.environ["BPS_REPO"])
from byteps_tpu.config import Config
from byteps_tpu.core.registry import TensorRegistry
from byteps_tpu.core.types import DataType, RequestType, get_command_type
from byteps_tpu.server import run_server
from byteps_tpu.server.client import PSClient
from byteps_tpu.server.compressed import CompressedTensor

PORT = int(os.environ["BPS_STRESS_PORT"])
cfg = Config(num_workers=2, num_servers=1)
server = threading.Thread(target=run_server, args=(PORT, cfg), daemon=True)
server.start()

CMD = get_command_type(RequestType.DEFAULT_PUSH_PULL, DataType.FLOAT32)
addr = [f"127.0.0.1:{PORT}"]
clients = [PSClient(addr, worker_id=w) for w in range(2)]

def reg():
    return TensorRegistry(Config(num_workers=2, num_servers=1))

def worker(w):
    r = reg()
    c = clients[w]
    rng = np.random.RandomState(w)
    # dense tensors (multi-partition) + compressed tensor, interleaved
    ctxs = [r.init_tensor(f"t{i}", 3000 * 4, DataType.FLOAT32)
            for i in range(4)]
    for ctx in ctxs:
        c.init_tensor(ctx, np.zeros(3000, np.float32))
    ct = CompressedTensor(c, r.init_tensor("comp", 2048 * 4, DataType.FLOAT32),
                          {"compressor": "onebit", "ef": "vanilla"}, 2)
    # dedicated keys for the fault-tolerance wire paths: epoch-stamped
    # pushes with a deliberate REPLAY (server-side last_round dedup) and
    # the fused PUSHPULL op carrying the same stamps
    rctx = r.init_tensor("replay", 1024 * 4, DataType.FLOAT32)
    c.init_tensor(rctx, np.zeros(1024, np.float32))
    fctx = r.init_tensor("fusedep", 1024 * 4, DataType.FLOAT32)
    c.init_tensor(fctx, np.zeros(1024, np.float32))
    # bounded-staleness window key (BYTEPS_STALENESS=1 in the test
    # env): worker 0 pushes one round AHEAD of the open round every
    # step, so DeferFold's payload copy, WindowPublishLocked's
    # pub_hist ring + selective parked-pull scan and the out-of-lock
    # RedispatchDeferred all race the data plane under the sanitizer
    wctx = r.init_tensor("window", 1024 * 4, DataType.FLOAT32)
    c.init_tensor(wctx, np.zeros(1024, np.float32))
    # descriptor-tier key (>= 64KB): over the shm transport the payload
    # rides the ring as an 8-byte descriptor and the server folds it IN
    # PLACE from the shared arena — worker 0's push lands in the key's
    # accumulator (zero-copy first fold), worker 1's goes through the
    # per-engine fold SCRATCH, and the test env's small arena forces the
    # block ring to wrap+reclaim while both workers race. The perf-PR
    # additions (SIMD fold, OOB descriptors, buffer pool) are all inside
    # this loop's shadow under the sanitizer.
    octx = r.init_tensor("oob", 24 * 1024 * 4, DataType.FLOAT32)
    c.init_tensor(octx, np.zeros(24 * 1024, np.float32))
    for step in range(15):
        for ctx in ctxs:
            x = rng.randn(3000).astype(np.float32)
            c.push_pull(ctx, x, average=True, num_workers=2)
        # training-health leg (BYTEPS_HEALTH=1 in the test env): the
        # fused in-fold stat kernel ran on the folds above; the keyed
        # HEALTH_PULL control op races the data plane inline on the
        # conn loop, and both workers read the same KeyStore hstat
        # the engines publish under ks.mu
        hp = ctxs[step % len(ctxs)].partitions[0]
        c.health_pull(hp.server, hp.key, timeout_s=5)
        ct.push_pull(rng.randn(2048).astype(np.float32))
        # descriptor-tier round: arena in-place fold + fold scratch +
        # block reclaim, raced by both workers every step
        c.push_pull(octx, rng.randn(24 * 1024).astype(np.float32),
                    average=True, num_workers=2)
        # async-push path (detached waiters drain in RecvLoop while the
        # paired pull waits on the same key-affine conn): the round-4
        # concurrency addition, stressed under the sanitizer like the
        # rest of the protocol
        actx = ctxs[step % len(ctxs)]
        for p in actx.partitions:
            c.zpush_async(p.server, p.key,
                          rng.randn(p.length // 4).astype(np.float32), CMD)
        for p in actx.partitions:
            out = np.empty(p.length // 4, np.float32)
            c.zpull(p.server, p.key, out, CMD)
        # replay/dedup path (round 6 fault-tolerance addition): each
        # worker pushes its epoch-stamped contribution TWICE — the
        # server must fold it once (last_round dedup) and both engine
        # threads race on the same KeyStore's last_round vector
        ep = (step + 1) << 16
        rp = rctx.partitions[0]
        rbuf = rng.randn(1024).astype(np.float32)
        c.zpush(rp.server, rp.key, rbuf, CMD, epoch=ep)
        c.zpush(rp.server, rp.key, rbuf, CMD, epoch=ep | 1)  # replay
        rout = np.empty(1024, np.float32)
        c.zpull(rp.server, rp.key, rout, CMD)
        # fused PUSHPULL with the same stamp: parked fused replies +
        # the completion reactor under the sanitizer
        fp = fctx.partitions[0]
        fdone = threading.Event()
        fout = np.empty(1024 * 4, np.uint8)
        c.zpushpull_async(fp.server, fp.key,
                          rng.randn(1024).astype(np.float32), fout, CMD,
                          lambda n, err, d=fdone: d.set(), epoch=ep)
        assert fdone.wait(60), "fused completion never fired"
        # staleness-window round: both workers fold round step+1; w0
        # then BLOCKS on a deliberately ahead round step+2 fold — it
        # parks in the window, w1's aligned fold publishes and the
        # redispatch replies it (the blocking wait also fences w0 to
        # skew <= 1, keeping every fold inside window W). Next step's
        # own push of that round is then epoch-deduped (last_round
        # raced by both engines).
        wp = wctx.partitions[0]
        wbuf = np.ones(1024, np.float32)
        c.zpush(wp.server, wp.key, wbuf, CMD, epoch=(step + 1) << 16)
        if w == 0:
            c.zpush(wp.server, wp.key, wbuf, CMD, epoch=(step + 2) << 16)
        # Waiter-lifecycle burst (the PR-6 TSAN finding's minimal
        # repro, promoted): tight concurrent BLOCKING request loops on
        # shared striped conns churn Waiter completions across threads
        # — before the per-conn Waiter pool + explicitly-initialized
        # pthread primitives, heap/address reuse of completed Waiters
        # reported "double lock of a destroyed mutex" within seconds
        bctx = ctxs[(step + 1) % len(ctxs)]
        for bp in bctx.partitions:
            for _ in range(3):
                c.zpush(bp.server, bp.key,
                        rng.randn(bp.length // 4).astype(np.float32),
                        CMD)
                small = np.empty(bp.length // 4, np.float32)
                c.zpull(bp.server, bp.key, small, CMD)
        c.barrier()

threads = [threading.Thread(target=worker, args=(w,)) for w in range(2)]
for t in threads: t.start()

# Elastic leg (PR 13), CONCURRENT with the stress above: a second
# server starts at runtime and both clients AddServer it — the atomic
# conn-group publish (fixed array + release-store count) races the
# live recv loops, reactor sweeps and ServerDead probes under the
# sanitizer; then the new JOIN_PROBE / DRAIN_REQ control ops run
# inline on the conn loop while data traffic flows.
from byteps_tpu.utils.net import wait_port
PORT2 = int(os.environ["BPS_STRESS_PORT2"])
server2 = threading.Thread(target=run_server,
                           args=(PORT2, Config(num_workers=2,
                                               num_servers=1)),
                           daemon=True)
server2.start()
wait_port(PORT2)
assert clients[0].add_server(f"127.0.0.1:{PORT2}") == 1
assert clients[1].add_server(f"127.0.0.1:{PORT2}") == 1
probe = clients[0].join_probe(1)
assert probe and probe["num_workers"] == 2 and not probe["draining"]
ez = np.zeros(1024, np.float32)
it = threading.Thread(target=clients[0].init_key,
                      args=(1, 777, ez, CMD), daemon=True)
it.start()
clients[1].init_key(1, 777, ez, CMD)
it.join(timeout=30)
assert not it.is_alive()
for w in range(2):
    clients[w].zpush(1, 777, np.ones(1024, np.float32), CMD,
                     epoch=(1 << 16))
eout = np.empty(1024, np.float32)
clients[0].zpull(1, 777, eout, CMD)
assert (eout == 2.0).all()
ack = clients[0].drain_req(1)
assert ack and ack["draining"] and ack["keys_held"] >= 1
stats = clients[1].server_stats(1)
assert stats and stats["draining"] == 1

for t in threads: t.join()
# the staleness window was armed (BYTEPS_STALENESS=1 rides the test
# env) and its bookkeeping slots published; whether a given run
# actually deferred is a scheduling race — the POINT of running it
# under the sanitizer — so only the no-reject invariant is hard
wstats = clients[0].server_stats(0)
assert "window_deferred" in wstats, wstats
assert wstats["window_rejected"] == 0, wstats
clients[0].close()  # both workers SHUTDOWN: both servers exit cleanly
clients[1].close()
server.join(timeout=20)
server2.join(timeout=20)
print("STRESS_OK")
"""


# Minimal deterministic repro of the PR-6 TSAN finding (the Waiter-pool
# regression class): tight concurrent BLOCKING push/pull loops from 4
# threads sharing one client's striped conns churn Waiter completions
# across threads. Before the per-conn Waiter pool + explicitly
# pthread-initialized Mu/Cv wrappers (PR 7 fix), heap/address reuse of
# completed Waiters produced ~510 "double lock of a destroyed mutex"
# reports within seconds of exactly this loop — so a regression fires
# fast and deterministically. Kept SMALL (4 threads x 60 rounds, one
# small key each + one shared contended key) so the whole test — TSAN
# build included, content-hash-cached across the session — fits the
# tier-1 budget; the full protocol burst stays in the slow tier above.
_WAITER_SMOKE = r"""
import threading, numpy as np
import os, sys
sys.path.insert(0, os.environ["BPS_REPO"])
from byteps_tpu.config import Config
from byteps_tpu.core.registry import TensorRegistry
from byteps_tpu.core.types import DataType, RequestType, get_command_type
from byteps_tpu.server import run_server
from byteps_tpu.server.client import PSClient

PORT = int(os.environ["BPS_STRESS_PORT"])
cfg = Config(num_workers=1, num_servers=1)
server = threading.Thread(target=run_server, args=(PORT, cfg), daemon=True)
server.start()

CMD = get_command_type(RequestType.DEFAULT_PUSH_PULL, DataType.FLOAT32)
client = PSClient([f"127.0.0.1:{PORT}"], worker_id=0)
reg = TensorRegistry(cfg)
ctxs = [reg.init_tensor(f"w{t}", 256 * 4, DataType.FLOAT32)
        for t in range(4)]
shared = reg.init_tensor("shared", 256 * 4, DataType.FLOAT32)
for ctx in ctxs + [shared]:
    client.init_tensor(ctx, np.zeros(256, np.float32))

def worker(t):
    rng = np.random.RandomState(t)
    own = ctxs[t].partitions[0]
    sp = shared.partitions[0]
    out = np.empty(256, np.float32)
    for _ in range(60):
        client.zpush(own.server, own.key,
                     rng.randn(256).astype(np.float32), CMD)
        client.zpull(own.server, own.key, out, CMD)
        # shared-key contention: Waiters of different threads complete
        # interleaved on the same striped conns
        client.zpush(sp.server, sp.key, np.ones(256, np.float32), CMD)
        client.zpull(sp.server, sp.key, out, CMD)

threads = [threading.Thread(target=worker, args=(t,)) for t in range(4)]
for t in threads: t.start()
for t in threads: t.join()
client.close()
server.join(timeout=20)
print("SMOKE_OK")
"""


# Striped-wire stress (PR 17): forced-TCP clients (BYTEPS_ENABLE_IPC=0)
# with 4 data stripes and an 8 KB chunk race multi-segment fused
# PUSHPULL reassembly + the reply tx rings against inline control ops
# (STATS_PULL / JOIN_PROBE / HEALTH_PULL on the never-queued conn-0
# lane), a mid-run single-stripe kill (server-side StripeReset + seq
# gate resync racing live segment writes), and an elastic join/drain.
_STRIPE_STRESS = r"""
import threading, time, numpy as np
import os, sys
sys.path.insert(0, os.environ["BPS_REPO"])
from byteps_tpu.config import Config
from byteps_tpu.core.types import DataType, RequestType, get_command_type
from byteps_tpu.server import run_server
from byteps_tpu.server.client import PSClient
from byteps_tpu.utils.net import wait_port

PORT = int(os.environ["BPS_STRESS_PORT"])
cfg = Config(num_workers=2, num_servers=1)
server = threading.Thread(target=run_server, args=(PORT, cfg), daemon=True)
server.start()
wait_port(PORT)
CMD = get_command_type(RequestType.DEFAULT_PUSH_PULL, DataType.FLOAT32)
addr = [f"127.0.0.1:{PORT}"]
clients = [PSClient(addr, worker_id=w) for w in range(2)]

N = 48 * 1024  # 192 KB -> ~24 segments per push at the 8 KB chunk
zero = np.zeros(N, np.float32)
its = []
for key in (300, 301, 302):
    t = threading.Thread(target=clients[1].init_key,
                         args=(0, key, zero, CMD), daemon=True)
    t.start()
    clients[0].init_key(0, key, zero, CMD)
    its.append(t)
for t in its:
    t.join(timeout=30)
    assert not t.is_alive(), "init barrier wedged"

def fused(c, key, x, out, epoch):
    done = threading.Event(); err = [None]
    def cb(n, e):
        err[0] = e; done.set()
    c.zpushpull_async(0, key, x, out, CMD, cb, epoch=epoch)
    assert done.wait(120), "fused pushpull timed out"
    if err[0]:
        raise err[0]

def worker(w):
    c = clients[w]
    out = np.empty(N, np.float32)
    for step in range(1, 11):
        ep = step << 16
        # sync mode: a round completes only when BOTH workers folded,
        # so both workers push every key; worker w contributes
        # (w+1)*step -> aggregate 3*step, asserted bitwise (multi-
        # segment reassembly from two senders interleaves on the same
        # engine threads)
        for key in (300, 301, 302):
            fused(c, key,
                  np.full(N, float(w + 1) * step, np.float32), out, ep)
            assert (out == 3.0 * step).all(), (w, step, key)
        # control ops race the striped data plane on the conn-0 lane
        st = c.server_stats(0)
        assert st is not None and st["stripe_segs"] > 0
        c.join_probe(0)
        c.health_pull(0, 300, timeout_s=5)
        if step == 5 and w == 0:
            # kill one of our data conns mid-run: the server's conn
            # loop races StripeReset/gate-resync with worker 1's live
            # segments; our next rounds stripe over the survivors
            assert c.kill_stripe(0, 2)
            time.sleep(0.2)

ths = [threading.Thread(target=worker, args=(w,)) for w in range(2)]
for t in ths: t.start()

# elastic leg, CONCURRENT with the striped stress: a second server
# joins at runtime, both clients build a striped conn group to it and
# run a striped round there, then a drain — the group publish and the
# JOIN_PROBE/DRAIN_REQ control ops race live stripe reassembly
PORT2 = int(os.environ["BPS_STRESS_PORT2"])
server2 = threading.Thread(target=run_server,
                           args=(PORT2, Config(num_workers=2,
                                               num_servers=1)),
                           daemon=True)
server2.start()
wait_port(PORT2)
assert clients[0].add_server(f"127.0.0.1:{PORT2}") == 1
assert clients[1].add_server(f"127.0.0.1:{PORT2}") == 1
ez = np.zeros(N, np.float32)
it = threading.Thread(target=clients[0].init_key,
                      args=(1, 400, ez, CMD), daemon=True)
it.start()
clients[1].init_key(1, 400, ez, CMD)
it.join(timeout=30)
assert not it.is_alive()

def efused(c, x, out):
    done = threading.Event(); err = [None]
    def cb(n, e):
        err[0] = e; done.set()
    c.zpushpull_async(1, 400, x, out, CMD, cb, epoch=(1 << 16))
    assert done.wait(120)
    if err[0]:
        raise err[0]

eo0 = np.empty(N, np.float32)
eo1 = np.empty(N, np.float32)
et = threading.Thread(target=efused,
                      args=(clients[1], np.full(N, 2.0, np.float32), eo1))
et.start()
efused(clients[0], np.full(N, 1.0, np.float32), eo0)
et.join(timeout=120)
assert (eo0 == 3.0).all() and (eo1 == 3.0).all(), "elastic striped sum"
ack = clients[0].drain_req(1)
assert ack and ack["draining"]

for t in ths: t.join()

for c in clients:
    ts = c.transport_stats()
    assert ts["stripe_segs"] > 0, "striper never engaged under stress"
clients[0].close()
clients[1].close()
server.join(timeout=20)
server2.join(timeout=20)
print("STRIPE_STRESS_OK")
"""


# glibc's dynamic-TLS teardown (_dl_deallocate_tls freeing a joined
# thread's DTV block) is a known TSAN false positive for thread_local
# in dlopen'd objects — see ci/tsan.supp for the full story
_TSAN_SUPP = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "ci", "tsan.supp")

_TIERS = {
    # mode -> (runtime lib, options env var, options, error marker)
    "thread": ("libtsan.so", "TSAN_OPTIONS",
               f"halt_on_error=1 exitcode=66 suppressions={_TSAN_SUPP}",
               "WARNING: ThreadSanitizer"),
    # leak detection would see the whole long-lived interpreter (numpy,
    # CPython arenas) — scope ASAN to memory-safety errors
    "address": ("libasan.so", "ASAN_OPTIONS",
                "detect_leaks=0 halt_on_error=1 exitcode=66",
                "ERROR: AddressSanitizer"),
}


@pytest.mark.slow
@pytest.mark.parametrize("mode", sorted(_TIERS))
def test_sanitized_loopback_stress(tmp_path, mode):
    """The concurrent loopback stress under TSAN (races) and ASAN (heap
    overflow / use-after-free) against the server stores, shm ring
    transport, and codec mirror."""
    from byteps_tpu.utils.net import free_port

    lib_name, opts_var, opts, marker = _TIERS[mode]
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    runtime = subprocess.run(
        ["g++", f"-print-file-name={lib_name}"], capture_output=True,
        text=True).stdout.strip()
    if not os.path.isabs(runtime) or not os.path.exists(runtime):
        pytest.skip(f"{lib_name} not available")

    script = tmp_path / "stress.py"
    script.write_text(_STRESS)
    port1 = free_port()
    port2 = free_port()
    while port2 == port1:
        port2 = free_port()
    env = {
        **os.environ,
        "BPS_REPO": repo,
        "BPS_STRESS_PORT": str(port1),
        # elastic leg: the runtime-joined second server
        "BPS_STRESS_PORT2": str(port2),
        "BYTEPS_SANITIZE": mode,
        "LD_PRELOAD": runtime,
        opts_var: opts,
        # small arena: the stress's 96KB descriptor-tier rounds wrap
        # and reclaim the block ring many times under the sanitizer
        "BYTEPS_IPC_ARENA_BYTES": str(512 << 10),
        # training-health leg: the in-fold stat pass (fused last-fold
        # kernel + publish scans) and the HEALTH_PULL control op run
        # under the sanitizer with both workers racing
        "BYTEPS_HEALTH": "1",
        # staleness-window leg: both stress servers construct with
        # window 1 so worker 0's deliberately ahead folds park in
        # DeferFold and redispatch at publish instead of rejecting
        "BYTEPS_STALENESS": "1",
        # jax under sanitizers is hopeless; the stress uses numpy only
        "JAX_PLATFORMS": "cpu",
    }
    # build the sanitized lib first (outside LD_PRELOAD; g++ subprocesses
    # under a preloaded runtime work but are slower)
    subprocess.run(
        [sys.executable, "-c",
         "import sys, os; sys.path.insert(0, os.environ['BPS_REPO']); "
         "from byteps_tpu.native.build import build; build(verbose=True)"],
        env={**os.environ, "BPS_REPO": repo, "BYTEPS_SANITIZE": mode},
        check=True, capture_output=True, timeout=300)

    proc = subprocess.run([sys.executable, str(script)], env=env,
                          capture_output=True, text=True, timeout=480)
    out = proc.stdout + proc.stderr
    assert marker not in out, out[-4000:]
    assert proc.returncode == 0, out[-4000:]
    assert "STRESS_OK" in out, out[-4000:]


@pytest.mark.slow
@pytest.mark.parametrize("mode", sorted(_TIERS))
def test_sanitized_stripe_stress(tmp_path, mode):
    """The striped cross-host wire plane under TSAN/ASAN: forced-TCP
    multi-segment fused traffic from two workers (reassembly + seq
    gates + reply tx rings + fused lossless decode paths all in the
    loop's shadow) raced against inline control ops, a mid-run
    single-stripe kill, and an elastic join/drain."""
    from byteps_tpu.utils.net import free_port

    lib_name, opts_var, opts, marker = _TIERS[mode]
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    runtime = subprocess.run(
        ["g++", f"-print-file-name={lib_name}"], capture_output=True,
        text=True).stdout.strip()
    if not os.path.isabs(runtime) or not os.path.exists(runtime):
        pytest.skip(f"{lib_name} not available")

    subprocess.run(
        [sys.executable, "-c",
         "import sys, os; sys.path.insert(0, os.environ['BPS_REPO']); "
         "from byteps_tpu.native.build import build; build(verbose=True)"],
        env={**os.environ, "BPS_REPO": repo, "BYTEPS_SANITIZE": mode},
        check=True, capture_output=True, timeout=300)

    script = tmp_path / "stripe_stress.py"
    script.write_text(_STRIPE_STRESS)
    port1 = free_port()
    port2 = free_port()
    while port2 == port1:
        port2 = free_port()
    env = {
        **os.environ,
        "BPS_REPO": repo,
        "BPS_STRESS_PORT": str(port1),
        "BPS_STRESS_PORT2": str(port2),
        "BYTEPS_SANITIZE": mode,
        "LD_PRELOAD": runtime,
        opts_var: opts,
        # the striped plane needs the real TCP wire; 4 data stripes at
        # an 8 KB chunk turn every 192 KB push into ~24 raced segments
        "BYTEPS_ENABLE_IPC": "0",
        "BYTEPS_WIRE_STRIPES": "4",
        "BYTEPS_STRIPE_CHUNK_BYTES": "8192",
        "BYTEPS_SOCK_BUF_BYTES": "65536",
        "BYTEPS_HEALTH": "1",
        "JAX_PLATFORMS": "cpu",
    }
    proc = subprocess.run([sys.executable, str(script)], env=env,
                          capture_output=True, text=True, timeout=480)
    out = proc.stdout + proc.stderr
    assert marker not in out, out[-4000:]
    assert proc.returncode == 0, out[-4000:]
    assert "STRIPE_STRESS_OK" in out, out[-4000:]


def test_tsan_waiter_pool_smoke(tmp_path):
    """Fast deterministic TSAN smoke (NOT slow — runs inside tier-1):
    the PR-6 Waiter-pool minimal repro. A regression in the per-conn
    Waiter pool or the pthread-initialized Mu/Cv wrappers reports
    "double lock of a destroyed mutex" within seconds of this loop,
    so the class is caught by the 870 s tier-1 gate instead of only by
    the slow sanitize burst. The TSAN build is content-hash-cached
    (~6 s cold on the 2-core box); the stress itself is ~4 threads x
    60 blocking rounds."""
    from byteps_tpu.utils.net import free_port

    lib_name, opts_var, opts, marker = _TIERS["thread"]
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    runtime = subprocess.run(
        ["g++", f"-print-file-name={lib_name}"], capture_output=True,
        text=True).stdout.strip()
    if not os.path.isabs(runtime) or not os.path.exists(runtime):
        pytest.skip(f"{lib_name} not available")

    subprocess.run(
        [sys.executable, "-c",
         "import sys, os; sys.path.insert(0, os.environ['BPS_REPO']); "
         "from byteps_tpu.native.build import build; build()"],
        env={**os.environ, "BPS_REPO": repo, "BYTEPS_SANITIZE": "thread"},
        check=True, capture_output=True, timeout=300)

    script = tmp_path / "waiter_smoke.py"
    script.write_text(_WAITER_SMOKE)
    env = {
        **os.environ,
        "BPS_REPO": repo,
        "BPS_STRESS_PORT": str(free_port()),
        "BYTEPS_SANITIZE": "thread",
        "LD_PRELOAD": runtime,
        opts_var: opts,
        "JAX_PLATFORMS": "cpu",
    }
    proc = subprocess.run([sys.executable, str(script)], env=env,
                          capture_output=True, text=True, timeout=240)
    out = proc.stdout + proc.stderr
    assert marker not in out, out[-4000:]
    assert proc.returncode == 0, out[-4000:]
    assert "SMOKE_OK" in out, out[-4000:]
