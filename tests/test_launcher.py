"""Launcher tests: core allocation knobs, per-rank env wiring + affinity of
spawned workers, role dispatch, and the multi-node command builder
(reference behaviors: launcher/launch.py:43-239, dist_launcher.py:36-100)."""

import json
import os
import subprocess
import sys

import pytest

from byteps_tpu.launcher import (
    _parse_core_list, allocate_cpu_cores, launch_workers, run_role,
)
from byteps_tpu.launcher.dist import build_commands, read_hostfile


def test_parse_core_list():
    assert _parse_core_list("0-3,8,10-11") == [0, 1, 2, 3, 8, 10, 11]
    assert _parse_core_list("") == []


def test_allocate_fair_share():
    sets = allocate_cpu_cores(2, avail=[0, 1, 2, 3])
    assert sets == [[0, 1], [2, 3]]


def test_allocate_visible_override(monkeypatch):
    monkeypatch.setenv("BYTEPS_VISIBLE_CPU_CORES", "0-1;6,7")
    assert allocate_cpu_cores(2) == [[0, 1], [6, 7]]
    with pytest.raises(ValueError):
        allocate_cpu_cores(3)


def test_allocate_blacklist_and_quota(monkeypatch):
    monkeypatch.setenv("BYTEPS_CPU_BLACKLIST", "0")
    monkeypatch.setenv("BYTEPS_NUMA_DEFAULT_QUOTA", "1")
    sets = allocate_cpu_cores(2, avail=[0, 1, 2, 3])
    assert sets == [[1], [2]]  # core 0 excluded, 1 core each


def test_allocate_more_workers_than_cores():
    sets = allocate_cpu_cores(3, avail=[0, 1])
    assert len(sets) == 3 and all(s for s in sets)


def test_launch_workers_env_and_affinity(tmp_path):
    """Each child sees its BYTEPS_LOCAL_RANK/SIZE and a pinned affinity."""
    out = tmp_path / "env"
    prog = (
        "import os, json, sys;"
        "json.dump({'rank': os.environ['BYTEPS_LOCAL_RANK'],"
        " 'size': os.environ['BYTEPS_LOCAL_SIZE'],"
        " 'aff': sorted(os.sched_getaffinity(0))},"
        " open(sys.argv[1] + os.environ['BYTEPS_LOCAL_RANK'], 'w'))"
    )
    rc = launch_workers([sys.executable, "-c", prog, str(out)], local_size=2)
    assert rc == 0
    recs = [json.load(open(f"{out}{r}")) for r in range(2)]
    assert [r["rank"] for r in recs] == ["0", "1"]
    assert all(r["size"] == "2" for r in recs)
    # disjointness only holds when the allocator had >= 2 physical units
    # (HT siblings of one core are a single unit, round-robined to both)
    expected = allocate_cpu_cores(2)
    if expected[0] and set(expected[0]).isdisjoint(expected[1]):
        assert set(recs[0]["aff"]).isdisjoint(recs[1]["aff"])


def test_launch_workers_propagates_failure():
    rc = launch_workers([sys.executable, "-c", "import sys; sys.exit(3)"],
                        local_size=1)
    assert rc == 3


def test_trace_dirs_created(tmp_path, monkeypatch):
    monkeypatch.setenv("BYTEPS_TRACE_ON", "1")
    monkeypatch.setenv("BYTEPS_TRACE_DIR", str(tmp_path / "tr"))
    rc = launch_workers([sys.executable, "-c", "pass"], local_size=2)
    assert rc == 0
    assert (tmp_path / "tr" / "0").is_dir() and (tmp_path / "tr" / "1").is_dir()


def test_scheduler_role_noop(monkeypatch):
    monkeypatch.setenv("DMLC_ROLE", "scheduler")
    assert run_role([]) == 0


def test_worker_role_requires_command(monkeypatch):
    monkeypatch.setenv("DMLC_ROLE", "worker")
    assert run_role([]) == 2


def test_cli_entry():
    rc = subprocess.run(
        [sys.executable, "-m", "byteps_tpu.launcher",
         sys.executable, "-c", "print('ok')"],
        capture_output=True, text=True,
        env={**os.environ, "BYTEPS_LOCAL_SIZE": "1",
             "JAX_PLATFORMS": "cpu"})
    assert rc.returncode == 0 and "ok" in rc.stdout


def test_dist_build_commands(tmp_path):
    wf = tmp_path / "workers.txt"
    wf.write_text("# comment\nw0\nw1\n\n")
    sf = tmp_path / "servers.txt"
    sf.write_text("s0\n")
    workers, servers = read_hostfile(str(wf)), read_hostfile(str(sf))
    assert workers == ["w0", "w1"] and servers == ["s0"]
    plans = build_commands(workers, servers, "10.0.0.1", 9100,
                           ["python", "train.py"],
                           extra_env={"FOO": "bar"})
    assert [p["role"] for p in plans] == ["server", "worker", "worker"]
    srv, w0, w1 = plans
    assert "export BYTEPS_SERVER_ID=0;" in srv["remote_cmd"]
    assert "export DMLC_WORKER_ID=0;" in w0["remote_cmd"]
    assert "export DMLC_WORKER_ID=1;" in w1["remote_cmd"]
    for p in plans:
        assert "export DMLC_NUM_WORKER=2;" in p["remote_cmd"]
        assert "export DMLC_NUM_SERVER=1;" in p["remote_cmd"]
        assert "export DMLC_PS_ROOT_URI=10.0.0.1;" in p["remote_cmd"]
        assert "export FOO=bar;" in p["remote_cmd"]
        assert p["ssh_cmd"].startswith("ssh ")
    assert "train.py" in w0["remote_cmd"] and "train.py" not in srv["remote_cmd"]


def test_dist_dry_run(tmp_path, capsys):
    from byteps_tpu.launcher.dist import main as dist_main
    wf = tmp_path / "w.txt"
    wf.write_text("h1\n")
    rc = dist_main(["--worker-hostfile", str(wf), "--dry-run",
                    "--", "python", "t.py"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "[worker@h1]" in out and "t.py" in out
