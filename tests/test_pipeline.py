"""Pipeline parallelism: pp-sharded forward/backward must match the dense
single-device model (the reference has no PP — SURVEY.md §2.8 — so the
oracle is our own dense path)."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import shard_map
from jax.sharding import PartitionSpec as P

from byteps_tpu.models import llama
from byteps_tpu.parallel import pipeline as pl
from byteps_tpu.parallel import sharding as sh
from byteps_tpu.parallel.mesh import DP_AXIS, PP_AXIS, make_mesh


def _cfg(n_layers=4):
    cfg = llama.LlamaConfig.tiny(vocab_size=64, seq=16)
    return dataclasses.replace(cfg, n_layers=n_layers,
                               dtype=jnp.float32)


def _data(cfg, batch=8):
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    tokens = jnp.asarray(
        np.random.RandomState(1).randint(0, cfg.vocab_size, (batch, 17)),
        jnp.int32)
    return params, tokens


PP_SPECS = sh.llama_pp_param_specs()


def test_pipeline_forward_matches_dense(devices):
    cfg = _cfg()
    params, tokens = _data(cfg)
    dense = llama.loss_fn(params, {"tokens": tokens}, cfg)

    mesh = make_mesh({PP_AXIS: 4}, devices[:4])
    f = shard_map(
        lambda p, t: llama.loss_fn_pp(p, {"tokens": t}, cfg,
                                      num_microbatches=2),
        mesh=mesh, in_specs=(PP_SPECS, P()), out_specs=P(),
        check_vma=False)
    pp = jax.jit(f)(params, tokens)
    np.testing.assert_allclose(np.asarray(pp), np.asarray(dense),
                               rtol=2e-5, atol=2e-6)


def test_pipeline_grads_match_dense(devices):
    cfg = _cfg()
    params, tokens = _data(cfg)
    dense_grads = jax.grad(
        lambda p: llama.loss_fn(p, {"tokens": tokens}, cfg))(params)

    mesh = make_mesh({PP_AXIS: 4}, devices[:4])

    def pp_grads(p, t):
        g = jax.grad(lambda q: llama.loss_fn_pp(
            q, {"tokens": t}, cfg, num_microbatches=2))(p)
        # pp-replicated leaves: per-stage partials -> sum across stages
        for k in ("embed", "final_norm", "lm_head"):
            g[k] = pl.replicated_grad_correction(g[k], PP_AXIS)
        return g

    grad_specs = dict(PP_SPECS)
    f = shard_map(pp_grads, mesh=mesh, in_specs=(PP_SPECS, P()),
                  out_specs=grad_specs, check_vma=False)
    g = jax.jit(f)(params, tokens)

    flat_d, _ = jax.tree_util.tree_flatten_with_path(dense_grads)
    flat_p = dict(jax.tree_util.tree_flatten_with_path(g)[0])
    for path, gd in flat_d:
        gp = flat_p[path]
        np.testing.assert_allclose(
            np.asarray(gp), np.asarray(gd), rtol=5e-4, atol=5e-5,
            err_msg=f"grad mismatch at {jax.tree_util.keystr(path)}")


def test_pipeline_microbatch_counts(devices):
    """Loss is invariant to the microbatch count (schedule-only knob)."""
    cfg = _cfg()
    params, tokens = _data(cfg)
    mesh = make_mesh({PP_AXIS: 4}, devices[:4])
    losses = []
    for m in (1, 2, 4, 8):
        f = shard_map(
            lambda p, t, m=m: llama.loss_fn_pp(p, {"tokens": t}, cfg,
                                               num_microbatches=m),
            mesh=mesh, in_specs=(PP_SPECS, P()), out_specs=P(),
            check_vma=False)
        losses.append(float(jax.jit(f)(params, tokens)))
    np.testing.assert_allclose(losses, losses[0], rtol=2e-5)


def test_pipeline_composes_with_dp(devices):
    """dp x pp mesh: batch sharded over dp, stages over pp, grads psum'd
    over dp — the full 2D layout on 8 virtual devices."""
    cfg = _cfg(n_layers=2)
    params, tokens = _data(cfg, batch=8)
    dense = llama.loss_fn(params, {"tokens": tokens}, cfg)

    mesh = make_mesh({DP_AXIS: 4, PP_AXIS: 2}, devices)

    def step(p, t):
        loss = llama.loss_fn_pp(p, {"tokens": t}, cfg, num_microbatches=2)
        return jax.lax.pmean(loss, DP_AXIS)

    f = shard_map(step, mesh=mesh, in_specs=(PP_SPECS, P(DP_AXIS)),
                  out_specs=P(), check_vma=False)
    out = jax.jit(f)(params, tokens)
    np.testing.assert_allclose(np.asarray(out), np.asarray(dense),
                               rtol=2e-5, atol=2e-6)


def test_pipeline_rejects_bad_microbatch():
    cfg = _cfg()
    with pytest.raises(ValueError, match="not divisible"):
        pl.pipeline_forward(
            jnp.zeros((7, 4)), {"w": jnp.zeros((1, 4, 4))},
            lambda h, p: h, num_microbatches=3)
