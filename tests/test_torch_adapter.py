"""byteps_tpu.torch adapter: Horovod-style surface over the DCN PS
(reference: byteps/torch/__init__.py, tests/test_mxnet.py semantics —
push_pull is identity at size 1 and averages across workers)."""

import threading

import numpy as np
import pytest
import torch

from byteps_tpu.config import Config
from byteps_tpu.core.registry import TensorRegistry
from byteps_tpu.core.types import DataType
from byteps_tpu.server import run_server
from byteps_tpu.server.client import PSClient

_PORT = [21800]


def _fresh_state():
    from byteps_tpu.core.state import GlobalState
    GlobalState._instance = None


@pytest.fixture()
def bpt(bps):
    """Torch adapter over the plain (no-PS) initialized core."""
    import byteps_tpu.torch as bpt_mod
    yield bpt_mod


@pytest.fixture()
def bpt_ps(monkeypatch):
    """Torch adapter over a 1-worker loopback PS (full distributed path)."""
    port = _PORT[0]
    _PORT[0] += 1
    monkeypatch.setenv("DMLC_NUM_WORKER", "1")
    monkeypatch.setenv("DMLC_NUM_SERVER", "1")
    monkeypatch.setenv("DMLC_PS_ROOT_URI", "127.0.0.1")
    monkeypatch.setenv("DMLC_PS_ROOT_PORT", str(port))
    monkeypatch.setenv("BYTEPS_FORCE_DISTRIBUTED", "1")
    server = threading.Thread(
        target=run_server,
        args=(port, Config(num_workers=1, num_servers=1)), daemon=True)
    server.start()
    _fresh_state()
    import byteps_tpu.torch as bpt_mod
    bpt_mod.init()
    yield bpt_mod
    bpt_mod.shutdown()
    server.join(timeout=10)
    _fresh_state()


def _toy_problem(seed=0):
    g = torch.Generator().manual_seed(seed)
    model = torch.nn.Sequential(
        torch.nn.Linear(8, 16), torch.nn.ReLU(), torch.nn.Linear(16, 1))
    x = torch.randn(64, 8, generator=g)
    y = x.sum(dim=1, keepdim=True)
    return model, x, y


def _train(model, x, y, opt, steps=30):
    losses = []
    for _ in range(steps):
        opt.zero_grad()
        loss = torch.nn.functional.mse_loss(model(x), y)
        loss.backward()
        opt.step()
        losses.append(float(loss.detach()))
    return losses


def test_push_pull_identity_single_worker(bpt):
    x = torch.randn(4, 5)
    out = bpt.push_pull(x, name="t_id")
    torch.testing.assert_close(out, x)
    # in-place variant
    y = x.clone()
    bpt.push_pull_inplace(y, name="t_id2")
    torch.testing.assert_close(y, x)


def test_push_pull_requires_name(bpt):
    with pytest.raises(ValueError, match="name"):
        bpt.push_pull_async(torch.randn(3))


def test_async_poll_synchronize(bpt):
    x = torch.randn(16)
    want = x.clone()
    h = bpt.push_pull_async(x, name="t_async")
    bpt.synchronize(h)
    torch.testing.assert_close(x, want)


def test_distributed_optimizer_trains(bpt):
    model, x, y = _toy_problem()
    opt = bpt.DistributedOptimizer(
        torch.optim.SGD(model.parameters(), lr=0.05),
        named_parameters=model.named_parameters())
    assert isinstance(opt, torch.optim.SGD)   # dynamic-subclass contract
    losses = _train(model, x, y, opt)
    assert losses[-1] < losses[0] * 0.5, losses


def test_distributed_optimizer_grad_accumulation(bpt):
    model, x, y = _toy_problem()
    opt = bpt.DistributedOptimizer(
        torch.optim.SGD(model.parameters(), lr=0.05),
        named_parameters=model.named_parameters(),
        backward_passes_per_step=2)
    losses = []
    for _ in range(20):
        opt.zero_grad()
        for _ in range(2):
            loss = torch.nn.functional.mse_loss(model(x), y)
            loss.backward()
        opt.step()
        losses.append(float(loss.detach()))
    assert losses[-1] < losses[0] * 0.5, losses


def test_broadcast_noop_single_worker(bpt):
    model, _, _ = _toy_problem()
    before = {k: v.clone() for k, v in model.state_dict().items()}
    bpt.broadcast_parameters(model.state_dict(), root_rank=0)
    for k, v in model.state_dict().items():
        torch.testing.assert_close(v, before[k])
    assert bpt.broadcast_object({"a": 1}, root_rank=0) == {"a": 1}


def test_distributed_optimizer_trains_via_ps(bpt_ps):
    model, x, y = _toy_problem()
    opt = bpt_ps.DistributedOptimizer(
        torch.optim.SGD(model.parameters(), lr=0.05),
        named_parameters=model.named_parameters())
    losses = _train(model, x, y, opt)
    assert losses[-1] < losses[0] * 0.5, losses


def test_fp16_compression_via_ps(bpt_ps):
    model, x, y = _toy_problem()
    opt = bpt_ps.DistributedOptimizer(
        torch.optim.Adam(model.parameters(), lr=0.01),
        named_parameters=model.named_parameters(),
        compression=bpt_ps.Compression.fp16)
    losses = _train(model, x, y, opt)
    assert losses[-1] < losses[0] * 0.5, losses


def test_broadcast_object_via_ps(bpt_ps):
    obj = {"step": 7, "arr": [1.0, 2.0, 3.0]}
    assert bpt_ps.broadcast_object(obj, root_rank=0) == obj


def test_broadcast_optimizer_state_via_ps(bpt_ps):
    model, x, y = _toy_problem()
    opt = torch.optim.Adam(model.parameters(), lr=0.01)
    _train(model, x, y, opt, steps=3)
    before = {k: {kk: (vv.clone() if torch.is_tensor(vv) else vv)
                  for kk, vv in st.items()}
              for k, st in opt.state_dict()["state"].items()}
    bpt_ps.broadcast_optimizer_state(opt, root_rank=0)
    after = opt.state_dict()
    assert after["param_groups"][0]["lr"] == 0.01
    # at 1 worker the broadcast is identity: the warm Adam moments must
    # SURVIVE the round trip intact (a no-op or state-corrupting
    # broadcast both fail here)
    assert set(after["state"]) == set(before)
    for k, st in before.items():
        for kk, vv in st.items():
            got = after["state"][k][kk]
            if torch.is_tensor(vv):
                assert torch.allclose(got.float(), vv.float(),
                                      rtol=1e-6), (k, kk)
                assert not torch.equal(vv, torch.zeros_like(vv)) or \
                    kk == "step"
            else:
                assert got == vv, (k, kk)


def test_ddp_wrapper_via_ps(bpt_ps):
    model, x, y = _toy_problem()
    # plain-backward reference on an identical copy: at 1 worker
    # push_pull is identity, so synced grads must EQUAL the local ones
    # (catches a sync_gradients that silently fails to write back)
    import copy

    ref = copy.deepcopy(model)
    loss_ref = torch.nn.functional.mse_loss(ref(x), y)
    loss_ref.backward()
    ddp = bpt_ps.DistributedDataParallel(model)
    loss = torch.nn.functional.mse_loss(ddp(x), y)
    loss.backward()
    ddp.sync_gradients()
    for p, pr in zip(model.parameters(), ref.parameters()):
        assert p.grad is not None
        assert torch.allclose(p.grad, pr.grad, rtol=1e-5, atol=1e-7)


def test_two_worker_mean(monkeypatch):
    """Worker 0 = the torch adapter; worker 1 = a raw PSClient on a thread.
    push_pull must return the cross-worker mean (the reference's
    test_byteps_push_pull sum semantics, tests/test_mxnet.py:60-125)."""
    port = _PORT[0]
    _PORT[0] += 1
    monkeypatch.setenv("DMLC_NUM_WORKER", "2")
    monkeypatch.setenv("DMLC_NUM_SERVER", "1")
    monkeypatch.setenv("DMLC_WORKER_ID", "0")
    monkeypatch.setenv("DMLC_PS_ROOT_URI", "127.0.0.1")
    monkeypatch.setenv("DMLC_PS_ROOT_PORT", str(port))
    monkeypatch.setenv("BYTEPS_FORCE_DISTRIBUTED", "1")
    server = threading.Thread(
        target=run_server,
        args=(port, Config(num_workers=2, num_servers=1)), daemon=True)
    server.start()
    _fresh_state()
    import byteps_tpu.torch as bpt_mod
    bpt_mod.init()
    try:
        x0 = np.random.RandomState(0).randn(128).astype(np.float32)
        x1 = np.random.RandomState(1).randn(128).astype(np.float32)

        reg = TensorRegistry(Config(num_workers=2, num_servers=1))
        c1 = PSClient([f"127.0.0.1:{port}"], worker_id=1)
        res = {}

        def w1():
            ctx = reg.init_tensor("t2w", x1.nbytes, DataType.FLOAT32)
            res["w1"] = c1.push_pull(ctx, x1, average=True, num_workers=2)

        th = threading.Thread(target=w1, daemon=True)
        th.start()
        out = bpt_mod.push_pull(torch.from_numpy(x0.copy()), name="t2w")
        th.join(timeout=30)
        assert not th.is_alive()
        want = (x0 + x1) / 2
        np.testing.assert_allclose(out.numpy(), want, rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(res["w1"], want, rtol=1e-5, atol=1e-6)
        c1.close(shutdown_servers=False)
    finally:
        bpt_mod.shutdown()
        server.join(timeout=10)
        _fresh_state()


def test_sparse_embedding_gradients(bpt_ps):
    """nn.Embedding(sparse=True) gradients ride the row-sparse wire; the
    optimizer sees the aggregated DENSE gradient and training matches a
    plain torch run (1 worker => identity aggregation)."""
    import numpy as np

    def build(seed):
        torch.manual_seed(seed)
        return torch.nn.Sequential(
            torch.nn.Embedding(50, 8, sparse=True),
            torch.nn.Flatten(),
            torch.nn.Linear(8 * 4, 5))

    ids = torch.from_numpy(
        np.random.RandomState(0).randint(0, 50, (16, 4)))
    y = torch.from_numpy(np.random.RandomState(1).randint(0, 5, 16))

    ref = build(3)
    # plain torch: sparse grads need dense optim only for SGD w/o momentum
    ro = torch.optim.SGD(ref.parameters(), lr=0.1)
    for _ in range(4):
        ro.zero_grad()
        torch.nn.functional.cross_entropy(ref(ids), y).backward()
        ro.step()

    model = build(3)
    opt = bpt_ps.DistributedOptimizer(
        torch.optim.SGD(model.parameters(), lr=0.1),
        named_parameters=model.named_parameters())
    for _ in range(4):
        opt.zero_grad()
        torch.nn.functional.cross_entropy(model(ids), y).backward()
        opt.step()
        assert model[0].weight.grad is None or \
            not model[0].weight.grad.is_sparse  # replaced with dense

    for (n1, p1), (n2, p2) in zip(ref.named_parameters(),
                                  model.named_parameters()):
        np.testing.assert_allclose(p1.detach().numpy(),
                                   p2.detach().numpy(),
                                   rtol=2e-5, atol=2e-5, err_msg=n1)


def test_bf16_push_pull_roundtrip(bpt_ps):
    """bfloat16 tensors must reach the wire (DataType.BFLOAT16) instead
    of crashing in .numpy() — round-4 review regression. Bit-exact
    through the 1-worker PS sum."""
    x = torch.randn(257, dtype=torch.float32).to(torch.bfloat16)
    out = bpt_ps.push_pull(x.clone(), average=True, name="bf16t")
    assert out.dtype == torch.bfloat16
    assert torch.equal(out, x)


def test_bf16_optimizer_grad_hook(bpt_ps):
    """A bf16 model trains through the grad-hook path (the hook exports
    grads host-side; bf16 previously raised inside backward)."""
    model = torch.nn.Linear(8, 4).to(torch.bfloat16)
    opt = bpt_ps.DistributedOptimizer(
        torch.optim.SGD(model.parameters(), lr=0.05),
        named_parameters=model.named_parameters())
    x = torch.randn(16, 8).to(torch.bfloat16)
    loss0 = None
    for _ in range(5):
        opt.zero_grad()
        loss = model(x).square().mean()
        loss.backward()
        opt.step()
        loss0 = loss0 if loss0 is not None else float(loss)
    assert float(loss) < loss0
