"""Streamed gradient export + sharded optimizer apply
(BYTEPS_STREAM_EXPORT / BYTEPS_SHARDED_APPLY, jax/train.py +
jax/optim.py): numerics parity of stream-export on vs off vs the
single-process baseline (dense, fused-bucket and compression-enabled
configs), bitwise parity of the sharded apply against the fused optax
apply for adam/sgd, the non-separable fallback, export-stage telemetry
(streamed-leaf counters + time-to-first-push), and production-order
priority pinning end to end."""

import contextlib
import os
import threading

import numpy as np
import optax
import pytest

from byteps_tpu.config import Config
from byteps_tpu.server import run_server

_PORT = [23600]


@contextlib.contextmanager
def _ps_env(extra_env: dict = None):
    from byteps_tpu.core.state import GlobalState

    port = _PORT[0]
    _PORT[0] += 1
    env = {
        "DMLC_NUM_WORKER": "1", "DMLC_NUM_SERVER": "1",
        "DMLC_PS_ROOT_URI": "127.0.0.1", "DMLC_PS_ROOT_PORT": str(port),
        "BYTEPS_FORCE_DISTRIBUTED": "1", **(extra_env or {}),
    }
    saved = {k: os.environ.get(k) for k in env}
    os.environ.update(env)
    server = threading.Thread(
        target=run_server,
        args=(port, Config(num_workers=1, num_servers=1)), daemon=True)
    server.start()
    GlobalState._instance = None
    import byteps_tpu as bps
    bps.init()
    try:
        yield bps
    finally:
        bps.shutdown()
        server.join(timeout=10)
        GlobalState._instance = None
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def _setup():
    import jax
    import jax.numpy as jnp

    from byteps_tpu.models import mlp

    cfg = mlp.MLPConfig(in_dim=64, hidden=(48, 32), n_classes=10)
    params = mlp.init_params(jax.random.PRNGKey(0), cfg)
    rng = np.random.RandomState(0)
    batch = {"x": jnp.asarray(rng.rand(32, 64), jnp.float32),
             "y": jnp.asarray(rng.randint(0, 10, 32), jnp.int32)}
    return cfg, params, batch


def _run_steps(params, batch, cfg, steps=3, tx=None, **kw):
    import jax
    import jax.numpy as jnp

    from byteps_tpu.core.state import get_state
    from byteps_tpu.jax.train import make_ps_train_step
    from byteps_tpu.models import mlp

    params = jax.tree.map(jnp.array, params)  # private copy (donation)
    tx = tx or optax.adam(1e-2)
    opt = tx.init(params)
    step = make_ps_train_step(lambda p, b: mlp.loss_fn(p, b, cfg), tx,
                              get_state().mesh, **kw)
    for _ in range(steps):
        params, opt, loss = step(params, opt, batch)
    return ([np.asarray(x) for x in jax.tree.leaves(params)],
            float(loss))


def _local_steps(params, batch, cfg, steps=3, tx=None):
    import jax

    from byteps_tpu.models import mlp

    tx = tx or optax.adam(1e-2)
    p, o = params, tx.init(params)

    def local(p, o, b):
        loss, g = jax.value_and_grad(lambda q: mlp.loss_fn(q, b, cfg))(p)
        u, o = tx.update(g, o, p)
        return optax.apply_updates(p, u), o, loss

    lj = jax.jit(local)
    for _ in range(steps):
        p, o, _ = lj(p, o, batch)
    return [np.asarray(x) for x in jax.tree.leaves(p)]


# --------------------------------------------------------------------- #
# parity: stream on vs off vs single-process baseline
# --------------------------------------------------------------------- #


# fusion 0 = every leaf rides its own key -> all stream ("dense");
# fusion 4096 = weights stream, biases ride the fused bucket
# ("fused-bucket"); the compression config exercises the host codec
# tier under streaming
@pytest.mark.parametrize("fusion,kw", [
    ("0", {}),
    ("4096", {}),
    ("0", dict(compression={"compressor": "onebit", "ef": "vanilla"},
               min_compress_bytes=0, device_compress=False)),
], ids=["dense", "fused-bucket", "onebit"])
def test_stream_on_off_parity(fusion, kw):
    """Stream-export on and off produce IDENTICAL params after 3 steps
    (the tap changes WHEN bytes leave the device, never what is
    computed), and both track the single-process baseline."""
    cfg, params, batch = _setup()
    with _ps_env({"BYTEPS_STREAM_EXPORT": "1",
                  "BYTEPS_FUSION_BYTES": fusion}) as bps:
        on, _ = _run_steps(params, batch, cfg, **kw)
        stats = bps.get_arena_stats()
        assert stats["export_streamed_leaves"] > 0, \
            "streaming never engaged — the on-arm is vacuous"
        assert stats["export_checkouts"] > 0
    with _ps_env({"BYTEPS_STREAM_EXPORT": "0",
                  "BYTEPS_FUSION_BYTES": fusion}) as bps:
        off, _ = _run_steps(params, batch, cfg, **kw)
        assert bps.get_arena_stats()["export_streamed_leaves"] == 0
    for a, b in zip(on, off):
        np.testing.assert_array_equal(a, b)
    if not kw:  # lossless transports also track the local baseline
        base = _local_steps(params, batch, cfg)
        for a, b in zip(on, base):
            np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-6)


def test_sharded_apply_on_off_parity():
    """BYTEPS_SHARDED_APPLY on vs off: identical params after 3 steps
    through the live PS path (per-leaf updates are bitwise the fused
    chain for adam)."""
    cfg, params, batch = _setup()
    with _ps_env({"BYTEPS_SHARDED_APPLY": "1"}):
        on, _ = _run_steps(params, batch, cfg)
    with _ps_env({"BYTEPS_SHARDED_APPLY": "0"}):
        off, _ = _run_steps(params, batch, cfg)
    for a, b in zip(on, off):
        np.testing.assert_array_equal(a, b)


# --------------------------------------------------------------------- #
# sharded apply: bitwise vs fused, separability detection
# --------------------------------------------------------------------- #


@pytest.mark.parametrize("mk_tx", [
    lambda: optax.adam(1e-3),
    lambda: optax.sgd(0.1),
    lambda: optax.sgd(0.1, momentum=0.9),
], ids=["adam", "sgd", "sgd-momentum"])
def test_sharded_apply_bitwise_vs_fused(mk_tx):
    """make_sharded_apply's per-leaf updates match the jitted fused
    optax apply BITWISE over multiple steps (same elementwise op
    sequence per leaf; the shared count increments identically)."""
    import jax
    import jax.numpy as jnp

    from byteps_tpu.jax.optim import make_sharded_apply

    tx = mk_tx()
    rng = np.random.RandomState(0)
    params = {"w": jnp.asarray(rng.randn(16, 8).astype(np.float32)),
              "b": jnp.asarray(rng.randn(8).astype(np.float32)),
              "nested": {"v": jnp.asarray(
                  rng.randn(4, 4).astype(np.float32))}}
    grads = jax.tree.map(
        lambda p: jnp.asarray(rng.randn(*p.shape).astype(np.float32)),
        params)
    st = tx.init(params)
    sa = make_sharded_apply(tx, params, st, donate=False)
    assert sa is not None, "elementwise chain not detected separable"

    def fused(p, s, g):
        u, s = tx.update(g, s, p)
        return optax.apply_updates(p, u), s

    fj = jax.jit(fused)
    pf, sf = params, st
    for _ in range(3):
        pf, sf = fj(pf, sf, grads)

    p_leaves = jax.tree.leaves(params)
    g_leaves = jax.tree.leaves(grads)
    ss = st
    for _ in range(3):
        res, newp = [], []
        for i in range(len(p_leaves)):
            np_, parts = sa.apply_leaf(p_leaves[i], ss, i, g_leaves[i])
            newp.append(np_)
            res.append(parts)
        p_leaves, ss = newp, sa.merge(ss, res)
    for a, b in zip(p_leaves, jax.tree.leaves(pf)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    for a, b in zip(jax.tree.leaves(ss), jax.tree.leaves(sf)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_sharded_apply_rejects_non_separable():
    """Global-norm clipping mixes leaves: the probe must detect it and
    return None (the train step then keeps the fused apply), and the
    PS train step must still train correctly through the fallback."""
    import jax.numpy as jnp

    from byteps_tpu.jax.optim import make_sharded_apply

    tx = optax.chain(optax.clip_by_global_norm(1.0), optax.adam(1e-2))
    params = {"w": jnp.ones((4, 4)), "b": jnp.ones((4,))}
    assert make_sharded_apply(tx, params, tx.init(params)) is None

    cfg, params, batch = _setup()
    with _ps_env({"BYTEPS_SHARDED_APPLY": "1"}):
        got, loss = _run_steps(params, batch, cfg, tx=tx)
    assert np.isfinite(loss)
    base = _local_steps(params, batch, cfg, tx=tx)
    for a, b in zip(got, base):
        np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-6)


# --------------------------------------------------------------------- #
# telemetry + production-order priority
# --------------------------------------------------------------------- #


def test_export_telemetry_and_production_priority():
    """The export-stage counters prove the overlap engaged (streamed
    leaves counted, TTFP recorded, arena export leases tagged), and
    the scheduler's pinned priorities come from measured first-export
    ordinals for every streamed key."""
    cfg, params, batch = _setup()
    with _ps_env({"BYTEPS_STREAM_EXPORT": "1",
                  "BYTEPS_FUSION_BYTES": "0"}) as bps:
        from byteps_tpu.core.state import get_state

        _run_steps(params, batch, cfg, steps=3)
        stats = bps.get_arena_stats()
        n_leaves = 6  # 3 layers x (w, b)
        assert stats["export_rounds"] == 3
        # every leaf streams every round (fusion off, no rowsparse)
        assert stats["export_streamed_leaves"] == 3 * n_leaves
        assert stats["export_fallback_leaves"] == 0
        assert stats["export_checkouts"] == 3 * n_leaves
        assert stats["export_ttfp_ms"] is not None
        assert stats["export_ttfp_ms"] > 0
        sched = get_state().scheduler
        order = sched.export_order()
        assert len(order) == n_leaves
        assert sorted(order.values()) == list(range(n_leaves))
        # the pinned priority of every streamed key IS -ordinal
        for key, o in order.items():
            assert sched._key_priority[key] == -o
    # stream off: counters stay flat, TTFP still measured (the loop's
    # first submit), so the bench can A/B both arms
    with _ps_env({"BYTEPS_STREAM_EXPORT": "0",
                  "BYTEPS_FUSION_BYTES": "0"}) as bps:
        _run_steps(params, batch, cfg, steps=2)
        stats = bps.get_arena_stats()
        assert stats["export_streamed_leaves"] == 0
        assert stats["export_fallback_leaves"] > 0
        assert stats["export_ttfp_ms"] is not None


def test_stream_rowsparse_leaves_fall_back():
    """rowsparse-routed leaves are excluded from streaming (the host
    row-sparse path needs the dense host rows) but the round's other
    leaves still stream — and numerics match the non-streamed run."""
    cfg, params, batch = _setup()
    kw = dict(rowsparse_params=("w0",))
    with _ps_env({"BYTEPS_STREAM_EXPORT": "1",
                  "BYTEPS_FUSION_BYTES": "0"}) as bps:
        on, _ = _run_steps(params, batch, cfg, **kw)
        stats = bps.get_arena_stats()
        assert stats["export_streamed_leaves"] > 0
        assert stats["export_fallback_leaves"] > 0  # the rowsparse leaf
    with _ps_env({"BYTEPS_STREAM_EXPORT": "0",
                  "BYTEPS_FUSION_BYTES": "0"}):
        off, _ = _run_steps(params, batch, cfg, **kw)
    for a, b in zip(on, off):
        np.testing.assert_array_equal(a, b)
