"""Chrome-trace Tracer window edges (utils/tracing.py): a span that
straddles trace_end_step must still close its TraceAnnotation (or every
later annotation on that pool thread nests inside the orphan forever),
the dump must stay valid JSON after an abnormal (exception) span exit,
and counter events ride the same window as spans."""

import json
import os

import jax
import pytest

from byteps_tpu.config import Config
from byteps_tpu.utils.tracing import Tracer


class _FakeAnnotation:
    """Stand-in for jax.profiler.TraceAnnotation that records its
    enter/exit balance (the real one is opaque)."""

    instances = []

    def __init__(self, name):
        self.name = name
        self.entered = 0
        self.exited = 0
        _FakeAnnotation.instances.append(self)

    def __enter__(self):
        self.entered += 1
        return self

    def __exit__(self, *exc):
        self.exited += 1
        return False


@pytest.fixture(autouse=True)
def _fresh_annotations(monkeypatch):
    _FakeAnnotation.instances = []
    monkeypatch.setattr(jax.profiler, "TraceAnnotation", _FakeAnnotation)
    yield


def _tracer(tmp_path, **kw):
    cfg = Config(trace_on=True, trace_start_step=0, trace_end_step=2,
                 trace_dir=str(tmp_path), jax_profiler_dir=str(tmp_path),
                 **kw)
    return Tracer(cfg)


def test_span_straddling_window_end_closes_annotation(tmp_path):
    tr = _tracer(tmp_path)
    tr.step()  # step 1, inside window
    tr.begin("t0", "PUSH.0")
    assert len(_FakeAnnotation.instances) == 1
    ann = _FakeAnnotation.instances[0]
    assert ann.entered == 1
    # the window closes while the span is still open (a slow partition
    # finishing after trace_end_step — the straddle case)
    tr.step()
    tr.step()  # step 3 > trace_end_step: flush fired, window closed
    tr.end("t0", "PUSH.0")
    assert ann.exited == 1, \
        "annotation must close even though the trace window ended"
    # and the flushed file is valid JSON
    out = tr.flush()
    if out is not None:  # events were flushed by step(); path may repeat
        with open(out) as f:
            json.load(f)


def test_dump_valid_json_after_abnormal_span_exit(tmp_path):
    tr = _tracer(tmp_path)
    tr.step()
    # normal complete span
    tr.begin("good", "PULL.0")
    tr.end("good", "PULL.0")
    # abnormal exit: the stage body raises; end() still runs from the
    # stage's finally (scheduler discipline) with the error in flight
    tr.begin("bad", "PUSH.0")
    try:
        raise RuntimeError("stage exploded")
    except RuntimeError:
        tr.end("bad", "PUSH.0")
    # orphan: begin with NO end at all (a crashed pool thread)
    tr.begin("orphan", "COMPRESS.0")
    out = tr.flush()
    assert out is not None and os.path.exists(out)
    with open(out) as f:
        data = json.load(f)
    names = {(e["tid"], e["name"]) for e in data["traceEvents"]
             if e["ph"] == "X"}
    assert ("good", "PULL.0") in names
    assert ("bad", "PUSH.0") in names, \
        "the abnormal-exit span must still record a complete event"
    assert ("orphan", "COMPRESS.0") not in names, \
        "an orphan open span must not emit a bogus event"


def test_double_begin_closes_orphan_annotation(tmp_path):
    tr = _tracer(tmp_path)
    tr.step()
    tr.begin("t", "PUSH.0")
    first = _FakeAnnotation.instances[0]
    tr.begin("t", "PUSH.0")  # double-begin without end
    assert first.exited == 1, \
        "the orphan annotation must close before the new one enters"
    second = _FakeAnnotation.instances[1]
    tr.end("t", "PUSH.0")
    assert second.exited == 1


def test_counter_events_ride_the_window(tmp_path):
    tr = _tracer(tmp_path)
    tr.step()
    tr.counter("bps:queue_depth_peak", {"depth": 7})
    for _ in range(3):
        tr.step()  # leave the window
    tr.counter("bps:queue_depth_peak", {"depth": 99})  # dropped
    out = tr.flush(path=str(tmp_path / "late"))
    with open(out) as f:
        data = json.load(f)
    counters = [e for e in data["traceEvents"] if e["ph"] == "C"]
    assert len(counters) == 1
    assert counters[0]["args"] == {"depth": 7}


def test_flush_with_no_events_returns_none(tmp_path):
    cfg = Config(trace_on=True, trace_start_step=5, trace_end_step=6,
                 trace_dir=str(tmp_path))
    tr = Tracer(cfg)
    tr.begin("t", "PUSH.0")  # outside window, no profiler dir: no-op
    tr.end("t", "PUSH.0")
    assert tr.flush() is None
