"""Step efficiency ledger (core/ledger.py) + perf regression gate
(ci/perf_gate.py): cost-analysis extraction with the no-backend
fallback, overlap-fraction math on synthetic span timelines, the
device-kind peak table with env override, archive JSONL round-trip +
SIGTERM flush, gate statistics (injected 20% regression on tight
synthetic histories trips; run-to-run noise replayed from the real
BENCH_r0x tails does not), and the loopback PS end-to-end: non-null
``mfu``/``overlap_frac``/``wire_efficiency`` in ``get_step_reports()``
with the efficiency verdict in ``classify_step``."""

import contextlib
import importlib.util
import json
import os
import signal
import subprocess
import sys
import threading
import time

import numpy as np
import optax
import pytest

from byteps_tpu.config import Config
from byteps_tpu.core import flight
from byteps_tpu.core.ledger import (
    EfficiencyLedger, PerfArchive, detect_peak, extract_cost, jit_cost,
    overlap_fraction, roofline_fraction,
)
from byteps_tpu.core.metrics import MetricsRegistry, StepReport, \
    classify_step
from byteps_tpu.server import run_server

REPO = os.path.join(os.path.dirname(__file__), "..")
_PORT = [24700]


def _load_perf_gate():
    spec = importlib.util.spec_from_file_location(
        "perf_gate", os.path.join(REPO, "ci", "perf_gate.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# --------------------------------------------------------------------- #
# peak table
# --------------------------------------------------------------------- #


def test_peak_table_device_kinds():
    for kind, want_f, want_bw in (("TPU v5 lite", 197e12, 819.0),
                                  ("TPU v5e", 197e12, 819.0),
                                  ("TPU v5p", 459e12, 2765.0),
                                  ("TPU v4", 275e12, 1228.0)):
        f, bw, src = detect_peak(kind, env={})
        assert (f, bw, src) == (want_f, want_bw, "table"), kind
    # "v5 lite" must win over the shorter "v5p"-style patterns — the
    # longest-substring-first contract
    f, _, _ = detect_peak("tpu V5 LITE", env={})
    assert f == 197e12


def test_peak_cpu_nominal_and_default():
    f, bw, src = detect_peak("cpu", env={})
    assert src == "cpu-nominal"
    assert f == (os.cpu_count() or 1) * 5e10
    f2, bw2, src2 = detect_peak("quantum-accelerator-9000", env={})
    assert src2 == "default" and f2 > 0 and bw2 > 0


def test_peak_env_override_wins():
    f, bw, src = detect_peak("TPU v4",
                             env={"BYTEPS_PEAK_FLOPS": "123e12",
                                  "BYTEPS_PEAK_BW_GBPS": "555"})
    assert (f, bw, src) == (123e12, 555.0, "env")
    # garbage override degrades to the table, never raises
    f, _, src = detect_peak("TPU v4", env={"BYTEPS_PEAK_FLOPS": "nan?"})
    assert (f, src) == (275e12, "table")


# --------------------------------------------------------------------- #
# cost-analysis extraction (version tolerance)
# --------------------------------------------------------------------- #


def test_extract_cost_real_jit():
    import jax
    import jax.numpy as jnp

    fn = jax.jit(lambda x: (x @ x).sum())
    c = jit_cost(fn, jnp.ones((64, 64), jnp.float32))
    assert c is not None and c["flops"] > 2 * 64 ** 3 * 0.9
    assert c.get("bytes_accessed", 0) > 0


class _Lowered:
    def __init__(self, ca):
        self._ca = ca

    def cost_analysis(self):
        if isinstance(self._ca, Exception):
            raise self._ca
        return self._ca


def test_extract_cost_shapes_and_failures():
    # legacy list-of-dicts shape
    c = extract_cost(_Lowered([{"flops": 10.0, "bytes accessed": 4.0}]))
    assert c == {"flops": 10.0, "bytes_accessed": 4.0}
    # dict without usable keys -> None, not {}
    assert extract_cost(_Lowered({"transcendentals": 3.0})) is None
    # raising backend -> None
    assert extract_cost(_Lowered(RuntimeError("no cost model"))) is None
    # NaN / zero placeholders are not costs
    assert extract_cost(_Lowered({"flops": float("nan")})) is None
    assert extract_cost(_Lowered({"flops": 0.0})) is None
    # non-lowerable callable -> None (the no-backend fallback path)
    assert jit_cost(object()) is None


# --------------------------------------------------------------------- #
# overlap / roofline math
# --------------------------------------------------------------------- #


def test_overlap_fraction_synthetic_timelines():
    # all wire inside compute -> fully hidden
    assert overlap_fraction([(0.1, 0.2), (0.3, 0.5)], 1.0) == 1.0
    # all wire after compute -> nothing hidden
    assert overlap_fraction([(2.0, 3.0)], 1.0) == 0.0
    # half the (single) span under compute
    assert overlap_fraction([(0.5, 1.5)], 1.0) == pytest.approx(0.5)
    # overlapping spans union-merge: [0,2] ∪ [1,3] = [0,3], 2/3 hidden
    assert overlap_fraction([(0.0, 2.0), (1.0, 3.0)], 2.0) == \
        pytest.approx(2.0 / 3.0)
    # no spans / degenerate spans -> None, never 0
    assert overlap_fraction([], 1.0) is None
    assert overlap_fraction([(1.0, 1.0)], 1.0) is None


def test_roofline_fraction():
    # intensity 10 FLOP/B x 100 GB/s = 1e12 attainable of 2e12 peak
    assert roofline_fraction(1000.0, 100.0, 2e12, 100.0) == \
        pytest.approx(0.5)
    # compute-bound shape caps at 1.0
    assert roofline_fraction(1e9, 1.0, 1e12, 100.0) == 1.0
    assert roofline_fraction(None, 100.0, 1e12, 100.0) is None
    assert roofline_fraction(1000.0, None, 1e12, 100.0) is None


# --------------------------------------------------------------------- #
# ledger pricing (unit: injected counters, no PS)
# --------------------------------------------------------------------- #


def _ledger(metrics=None, **cfg_kw):
    return EfficiencyLedger(Config(**cfg_kw), metrics)


def test_step_efficiency_fields():
    reg = MetricsRegistry()
    led = _ledger(reg, peak_flops=1e9, peak_bw_gbps=100.0)
    led.register_step_cost(flops=5e6, bytes_accessed=1e6,
                           ideal_wire_bytes=1000, source="xla")
    base = led.wire_bytes_total()
    reg.counter("wire/push_bytes").inc(1000)
    reg.counter("wire/pull_bytes").inc(1000)
    eff = led.step_efficiency(wall_s=0.01, compute_end_s=0.004,
                              wire_spans=[(0.002, 0.006)],
                              wire_base=base)
    assert eff["achieved_flops"] == pytest.approx(5e8)
    assert eff["mfu"] == pytest.approx(0.5)
    assert eff["overlap_frac"] == pytest.approx(0.5)
    assert eff["wire_bytes"] == 2000
    assert eff["wire_efficiency"] == pytest.approx(0.5)
    # intensity 5 FLOP/B x 100 GB/s = 5e11 >> 1e9 peak -> roofline 1.0
    assert eff["roofline_frac"] == 1.0
    # a report carrying these fields names the efficiency verdict
    r = StepReport(step=1, wall_ms=10.0, compute_ms=4.0,
                   mfu=eff["mfu"], roofline_frac=eff["roofline_frac"],
                   overlap_frac=eff["overlap_frac"],
                   wire_efficiency=eff["wire_efficiency"])
    msg = classify_step(r)
    assert "MFU 0.50 of 1.00 roofline" in msg
    assert "overlap 50%" in msg and "wire 2.0x ideal" in msg


def test_ledger_disabled_prices_nothing():
    led = _ledger(MetricsRegistry(), ledger=False)
    assert led.enabled is False
    led.register_step_cost(flops=1e6, ideal_wire_bytes=10)
    assert led.step_efficiency(0.01, 0.004, [(0.0, 0.01)], 0) == {}
    assert led.snapshot()["enabled"] is False


def test_missing_cost_model_degrades_per_field():
    """No cost analysis: MFU stays None but overlap/wire still price
    (the acceptance's 'never silently 0' contract)."""
    reg = MetricsRegistry()
    led = _ledger(reg)
    led.register_step_cost(flops=None, ideal_wire_bytes=100,
                           source="none")
    reg.counter("wire/push_bytes").inc(100)
    reg.counter("wire/pull_bytes").inc(100)
    eff = led.step_efficiency(0.01, 0.004, [(0.0, 0.002)], 0)
    assert "achieved_flops" not in eff and "mfu" not in eff
    assert eff["overlap_frac"] == 1.0
    assert eff["wire_efficiency"] == pytest.approx(0.5)


def test_monolithic_round_prices_no_overlap():
    """Device-compressed tier: export_done lands AFTER the wire, so
    spans would fabricate overlap_frac == 1.0 — a monolithic builder
    must price overlap as None while MFU/wire figures still land."""
    from byteps_tpu.core.metrics import StepProfiler

    reg = MetricsRegistry()
    led = _ledger(reg, peak_flops=1e9)
    led.register_step_cost(flops=1e6, ideal_wire_bytes=100,
                           source="xla")
    prof = StepProfiler(ledger=led)
    b = prof.begin_step()
    b.wire_span(b.t0 + 0.001, b.t0 + 0.002)
    b.monolithic = True
    b.mark("export_done")
    reg.counter("wire/push_bytes").inc(100)
    reg.counter("wire/pull_bytes").inc(100)
    r = prof.end_step(b)
    assert r.overlap_frac is None
    assert r.mfu is not None and r.wire_efficiency is not None
    # the same spans WITHOUT the monolithic latch price normally
    b2 = prof.begin_step()
    b2.wire_span(b2.t0 + 0.001, b2.t0 + 0.002)
    b2.mark("export_done")
    assert prof.end_step(b2).overlap_frac is not None


# --------------------------------------------------------------------- #
# efficiency_drop flight events
# --------------------------------------------------------------------- #


def test_efficiency_drop_flight_event():
    flight.configure(capacity=64, enabled=True)
    try:
        reg = MetricsRegistry()
        led = _ledger(reg, eff_drop_frac=0.25, eff_drop_window=8)
        # healthy plateau: window fills, nothing fires
        for i in range(6):
            led.on_step(StepReport(step=i + 1, mfu=0.40,
                                   overlap_frac=0.6))
        assert not [e for e in flight.get_recorder().events()
                    if e["kind"] == "efficiency_drop"]
        # a >25% cliff on mfu fires exactly one event for that metric
        led.on_step(StepReport(step=7, mfu=0.25, overlap_frac=0.6))
        drops = [e for e in flight.get_recorder().events()
                 if e["kind"] == "efficiency_drop"]
        assert len(drops) == 1 and "mfu" in drops[0]["detail"]
        assert drops[0]["key"] == 7  # the step number rides the event
        assert reg.counter("ledger/efficiency_drops").value == 1
        # warmup can't fire: < 4 samples in a fresh window
        led2 = _ledger(reg, eff_drop_frac=0.25, eff_drop_window=8)
        for i in range(3):
            led2.on_step(StepReport(step=i + 1, mfu=0.5))
        led2.on_step(StepReport(step=4, mfu=0.01))
        drops = [e for e in flight.get_recorder().events()
                 if e["kind"] == "efficiency_drop"]
        assert len(drops) == 1  # still only the first ledger's event
    finally:
        flight.configure(enabled=False)


# --------------------------------------------------------------------- #
# perf archive
# --------------------------------------------------------------------- #


def test_archive_jsonl_roundtrip(tmp_path):
    arch = PerfArchive(str(tmp_path), flush_steps=4)
    for i in range(10):
        arch.append({"step": i + 1, "wall_ms": 1.5 * (i + 1),
                     "mfu": 0.3})
    # buffered I/O: two flush boundaries passed, the tail is in memory
    with open(arch.path) as f:
        assert len(f.read().strip().splitlines()) == 8
    arch.flush()
    with open(arch.path) as f:
        lines = [json.loads(ln) for ln in f.read().strip().splitlines()]
    assert [r["step"] for r in lines] == list(range(1, 11))
    assert lines[4]["wall_ms"] == pytest.approx(7.5)
    assert arch.stats() == {"records": 10, "dropped": 0}


def test_archive_sigterm_flush(tmp_path):
    """SIGTERM must flush the buffered tail alongside the flight dump
    (the flight handler's term hooks). Run in a subprocess so the real
    signal path — handler, hooks, chain to default — is exercised; the
    script never imports jax, so this stays fast."""
    script = f"""
import os, signal, sys, time
sys.path.insert(0, {REPO!r})
from byteps_tpu.core import flight
from byteps_tpu.core.ledger import PerfArchive
flight.configure(capacity=16, enabled=True, dump_dir={str(tmp_path)!r})
flight.install_signal_handler()
arch = PerfArchive({str(tmp_path)!r}, flush_steps=1000)  # never auto
flight.add_term_hook(lambda: arch.flush(lock_timeout=1.0))  # prod shape
for i in range(7):
    arch.append({{"step": i + 1, "mfu": 0.4}})
print("READY", arch.path, flush=True)
time.sleep(30)
"""
    proc = subprocess.Popen([sys.executable, "-c", script],
                            stdout=subprocess.PIPE, text=True)
    try:
        line = proc.stdout.readline().split()
        assert line and line[0] == "READY"
        path = line[1]
        assert not os.path.exists(path) or \
            os.path.getsize(path) == 0  # nothing flushed yet
        proc.send_signal(signal.SIGTERM)
        proc.wait(timeout=20)
    finally:
        if proc.poll() is None:
            proc.kill()
    with open(path) as f:
        recs = [json.loads(ln) for ln in f.read().strip().splitlines()]
    assert [r["step"] for r in recs] == list(range(1, 8))


# --------------------------------------------------------------------- #
# perf regression gate (ci/perf_gate.py)
# --------------------------------------------------------------------- #


def test_gate_trips_injected_regression():
    pg = _load_perf_gate()
    baseline = {"keys": {"pushpull_dense_gbps": {
        "samples": [10.0, 10.1, 9.9, 10.05, 9.95]}}}
    # 20% down on a tight history: far past max(10% floor, 3 sigma)
    rep = pg.compare({"pushpull_dense_gbps": 8.0}, baseline)
    assert not rep["ok"]
    assert rep["regressions"][0]["key"] == "pushpull_dense_gbps"
    # within the noise band: passes
    assert pg.compare({"pushpull_dense_gbps": 9.85}, baseline)["ok"]
    # a big IMPROVEMENT is never a regression (directionality)
    rep = pg.compare({"pushpull_dense_gbps": 20.0}, baseline)
    assert rep["ok"]
    assert rep["rows"][0]["verdict"] == "improvement"


def test_gate_directionality_lower_is_better():
    pg = _load_perf_gate()
    baseline = {"keys": {"arena_on_step_ms": {
        "samples": [5.0, 5.05, 4.95]}}}
    rep = pg.compare({"arena_on_step_ms": 6.2}, baseline)  # 24% slower
    assert not rep["ok"]
    assert pg.compare({"arena_on_step_ms": 4.0}, baseline)["ok"]
    # unknown-direction keys are skipped, never guessed
    rep = pg.compare({"mystery_quantity": 1.0},
                     {"keys": {"mystery_quantity": {"samples": [2.0]}}})
    assert rep["ok"] and rep["rows"][0]["verdict"] == "skipped"
    # explicit per-key override beats the suffix table
    rep = pg.compare(
        {"weird_gbps": 1.0},
        {"keys": {"weird_gbps": {"samples": [2.0],
                                 "direction": "lower"}}})
    assert rep["ok"] and rep["rows"][0]["verdict"] == "improvement"


def test_gate_noise_replay_from_real_bench_tails():
    """Run-to-run noise replayed from the REAL BENCH_r0x artifacts must
    not trip the committed baseline: r03's dense 2.155 vs r04's 2.923
    is a 26% historical swing, and the MAD band absorbs replaying
    either round. A wedged round (r05, parsed null) reads as missing,
    never as a loss."""
    pg = _load_perf_gate()
    baseline = pg.load_baseline(
        os.path.join(REPO, "ci", "perf_baseline.json"))
    for r in (3, 4, 5):
        cand = pg.load_candidate(
            os.path.join(REPO, f"BENCH_r0{r}.json"))
        rep = pg.compare(cand, baseline)
        assert rep["ok"], (r, rep["regressions"])
    # r05 parsed null: every key missing, zero checked, still ok
    rep = pg.compare(pg.load_candidate(
        os.path.join(REPO, "BENCH_r05.json")), baseline)
    assert rep["checked"] == 0
    assert all(r["verdict"] == "missing" for r in rep["rows"])


def test_gate_archive_candidate(tmp_path):
    """A BYTEPS_PERF_ARCHIVE JSONL is a first-class gate candidate:
    numeric keys collapse to their median over the records."""
    pg = _load_perf_gate()
    path = tmp_path / "perf-123.jsonl"
    with open(path, "w") as f:
        for i in range(9):
            f.write(json.dumps({"step": i + 1, "wall_ms": 10.0 + i,
                                "mfu": 0.30 + 0.01 * i}) + "\n")
    cand = pg.load_candidate(str(path))
    assert cand["wall_ms"] == 14.0 and cand["mfu"] == \
        pytest.approx(0.34)
    baseline = {"keys": {"mfu": {"samples": [0.33, 0.35, 0.34]}}}
    assert pg.compare(cand, baseline)["ok"]
    baseline = {"keys": {"mfu": {"samples": [0.50, 0.51, 0.49]}}}
    assert not pg.compare(cand, baseline)["ok"]


def test_gate_cli_exit_codes(tmp_path):
    gate = os.path.join(REPO, "ci", "perf_gate.py")
    base = tmp_path / "base.json"
    base.write_text(json.dumps(
        {"keys": {"x_gbps": {"samples": [10.0, 10.1, 9.9]}}}))
    good = tmp_path / "good.json"
    good.write_text(json.dumps({"x_gbps": 10.0}))
    bad = tmp_path / "bad.json"
    bad.write_text(json.dumps({"x_gbps": 7.0}))
    assert subprocess.run(
        [sys.executable, gate, "--baseline", str(base),
         "--candidate", str(good)]).returncode == 0
    assert subprocess.run(
        [sys.executable, gate, "--baseline", str(base),
         "--candidate", str(bad)]).returncode == 1
    assert subprocess.run(
        [sys.executable, gate, "--baseline", str(base)],
        stderr=subprocess.DEVNULL).returncode == 2


# --------------------------------------------------------------------- #
# loopback PS end-to-end (the acceptance run)
# --------------------------------------------------------------------- #


@contextlib.contextmanager
def _ps_env(extra_env: dict = None):
    from byteps_tpu.core.state import GlobalState

    port = _PORT[0]
    _PORT[0] += 1
    env = {
        "DMLC_NUM_WORKER": "1", "DMLC_NUM_SERVER": "1",
        "DMLC_PS_ROOT_URI": "127.0.0.1", "DMLC_PS_ROOT_PORT": str(port),
        "BYTEPS_FORCE_DISTRIBUTED": "1", **(extra_env or {}),
    }
    saved = {k: os.environ.get(k) for k in env}
    os.environ.update(env)
    server = threading.Thread(
        target=run_server,
        args=(port, Config(num_workers=1, num_servers=1)), daemon=True)
    server.start()
    GlobalState._instance = None
    import byteps_tpu as bps
    bps.init()
    try:
        yield bps
    finally:
        bps.shutdown()
        server.join(timeout=10)
        GlobalState._instance = None
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def _train_rounds(steps=3, **kw):
    import jax
    import jax.numpy as jnp

    from byteps_tpu.core.state import get_state
    from byteps_tpu.jax.train import make_ps_train_step
    from byteps_tpu.models import mlp

    cfg = mlp.MLPConfig(in_dim=64, hidden=(48, 32), n_classes=10)
    params = mlp.init_params(jax.random.PRNGKey(0), cfg)
    rng = np.random.RandomState(0)
    batch = {"x": jnp.asarray(rng.rand(32, 64), jnp.float32),
             "y": jnp.asarray(rng.randint(0, 10, 32), jnp.int32)}
    tx = optax.adam(1e-2)
    opt = tx.init(params)
    step = make_ps_train_step(lambda p, b: mlp.loss_fn(p, b, cfg), tx,
                              get_state().mesh, **kw)
    for _ in range(steps):
        params, opt, loss = step(params, opt, batch)
    return float(loss)


def test_loopback_ledger_end_to_end(tmp_path):
    """The acceptance run: a loopback PS train carries non-null
    ``mfu``/``overlap_frac``/``wire_efficiency``, classify_step emits
    the efficiency verdict, get_ledger() names the cost source, and
    the perf archive holds one record per step after shutdown."""
    arch_dir = str(tmp_path / "perf")
    with _ps_env({"BYTEPS_PERF_ARCHIVE": arch_dir}) as bps:
        _train_rounds(steps=4)
        reports = bps.get_step_reports()
        assert len(reports) == 4
        last = reports[-1]
        assert last["mfu"] is not None and last["mfu"] > 0
        assert last["overlap_frac"] is not None
        assert 0.0 <= last["overlap_frac"] <= 1.0
        assert last["wire_efficiency"] is not None
        assert last["wire_efficiency"] > 0
        assert last["achieved_flops"] > 0
        assert last["wire_bytes"] > 0
        # ideal = every leaf once each way; actual dense wire carries
        # at least that, so efficiency can't exceed ~1 on this run
        assert last["wire_efficiency"] <= 1.01
        diag = bps.get_metrics()["steps"]["last_diagnosis"]
        assert "MFU" in diag and "overlap" in diag and "ideal" in diag
        led = bps.get_ledger()
        assert led["enabled"] is True and led["source"] == "xla"
        assert led["model_flops"] > 0 and led["ideal_wire_bytes"] > 0
        assert led["peak_flops"] > 0
        assert led["archive_records"] == 4
        # instrument mirror: last-step gauges + Prometheus face
        m = bps.get_metrics()
        assert m["gauges"]["ledger/mfu"] == pytest.approx(last["mfu"])
        arch_path = led["archive_path"]
    # shutdown flushed the tail
    with open(arch_path) as f:
        recs = [json.loads(ln) for ln in f.read().strip().splitlines()]
    assert [r["step"] for r in recs] == [1, 2, 3, 4]
    assert recs[-1]["mfu"] is not None and recs[-1]["wall_ms"] > 0


def test_ledger_re_engages_after_resume():
    """suspend/resume replaces state.ledger; a step closure built
    BEFORE the cycle must re-register its cost model on the fresh
    instance (the cache is keyed on the ledger identity, not just the
    plan) — found by the verify drive: post-resume MFU read None."""
    import jax
    import jax.numpy as jnp

    from byteps_tpu.core.state import get_state
    from byteps_tpu.jax.train import make_ps_train_step
    from byteps_tpu.models import mlp

    with _ps_env() as bps:
        cfg = mlp.MLPConfig(in_dim=64, hidden=(48, 32), n_classes=10)
        params = mlp.init_params(jax.random.PRNGKey(0), cfg)
        rng = np.random.RandomState(0)
        batch = {"x": jnp.asarray(rng.rand(32, 64), jnp.float32),
                 "y": jnp.asarray(rng.randint(0, 10, 32), jnp.int32)}
        tx = optax.adam(1e-2)
        opt = tx.init(params)
        step = make_ps_train_step(
            lambda p, b: mlp.loss_fn(p, b, cfg), tx, get_state().mesh)
        for _ in range(2):
            params, opt, _ = step(params, opt, batch)
        assert bps.get_step_reports()[-1]["mfu"] is not None
        bps.suspend()
        bps.resume(num_workers=1, num_servers=1)
        for _ in range(2):
            params, opt, _ = step(params, opt, batch)
        last = bps.get_step_reports()[-1]
        assert last["mfu"] is not None
        assert last["wire_efficiency"] is not None


def test_loopback_ledger_off_leaves_fields_none():
    with _ps_env({"BYTEPS_LEDGER": "0"}) as bps:
        _train_rounds(steps=2)
        last = bps.get_step_reports()[-1]
        assert last["mfu"] is None
        assert last["overlap_frac"] is None
        assert last["wire_efficiency"] is None
        assert bps.get_ledger()["enabled"] is False
        # the verdict gracefully omits the efficiency clause
        assert "MFU" not in bps.get_metrics()["steps"]["last_diagnosis"]
