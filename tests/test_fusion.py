"""Gradient bucket fusion (BYTEPS_FUSION_BYTES, jax/train.py): small
leaves ride one fused key per dtype run; numerics must be unchanged, the
min_compress_bytes gate must survive fusion (sub-threshold tensors stay
full-precision even though they travel fused), and fusion must actually
reduce declared keys."""

import os
import threading

import numpy as np
import optax
import pytest

from byteps_tpu.config import Config
from byteps_tpu.server import run_server

_PORT = [21800]


@pytest.fixture()
def ps_env(monkeypatch):
    from byteps_tpu.core.state import GlobalState

    port = _PORT[0]
    _PORT[0] += 1
    monkeypatch.setenv("DMLC_NUM_WORKER", "1")
    monkeypatch.setenv("DMLC_NUM_SERVER", "1")
    monkeypatch.setenv("DMLC_PS_ROOT_URI", "127.0.0.1")
    monkeypatch.setenv("DMLC_PS_ROOT_PORT", str(port))
    monkeypatch.setenv("BYTEPS_FORCE_DISTRIBUTED", "1")
    server = threading.Thread(
        target=run_server,
        args=(port, Config(num_workers=1, num_servers=1)), daemon=True)
    server.start()

    GlobalState._instance = None
    import byteps_tpu as bps
    bps.init()
    yield bps
    bps.shutdown()
    server.join(timeout=10)
    GlobalState._instance = None


def _mlp_setup():
    import jax
    from byteps_tpu.models import mlp

    cfg = mlp.MLPConfig(in_dim=64, hidden=(32, 32), n_classes=10)
    params = mlp.init_params(jax.random.PRNGKey(0), cfg)
    rng = np.random.RandomState(0)
    import jax.numpy as jnp
    batch = {"x": jnp.asarray(rng.rand(32, 64), jnp.float32),
             "y": jnp.asarray(rng.randint(0, 10, 32), jnp.int32)}
    return cfg, params, batch


def _run_steps(ps_env, params, batch, cfg, steps=5, **kw):
    import jax
    import jax.numpy as jnp
    from byteps_tpu.core.state import get_state
    from byteps_tpu.jax.train import make_ps_train_step
    from byteps_tpu.models import mlp

    # the PS step donates params/opt buffers — run on a private copy so
    # callers can reuse the originals for comparison runs
    params = jax.tree.map(jnp.array, params)
    tx = optax.sgd(0.05)
    opt = tx.init(params)
    step = make_ps_train_step(lambda p, b: mlp.loss_fn(p, b, cfg), tx,
                              get_state().mesh, **kw)
    for _ in range(steps):
        params, opt, loss = step(params, opt, batch)
    return jax.tree_util.tree_leaves(params), float(loss)


def test_fused_matches_local(ps_env):
    """Fusion on (default): PS step numerics == local step numerics."""
    import jax
    import optax as ox
    from byteps_tpu.models import mlp

    cfg, params, batch = _mlp_setup()
    got, _ = _run_steps(ps_env, params, batch, cfg)

    tx = ox.sgd(0.05)
    p, o = params, tx.init(params)

    def local(p, o, b):
        loss, g = jax.value_and_grad(lambda q: mlp.loss_fn(q, b, cfg))(p)
        u, o = tx.update(g, o, p)
        return ox.apply_updates(p, u), o, loss

    lj = jax.jit(local)
    for _ in range(5):
        p, o, _ = lj(p, o, batch)
    for a, b in zip(got, jax.tree_util.tree_leaves(p)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-6)


def test_fusion_reduces_keys(ps_env):
    """The MLP's 6 leaves (all sub-threshold here) must declare FEWER
    keys than leaves — the whole point of the bucket."""
    from byteps_tpu.core.state import get_state

    cfg, params, batch = _mlp_setup()
    _run_steps(ps_env, params, batch, cfg, steps=2)
    names = [c.name for c in get_state().registry.contexts_in_order()]
    fused = [n for n in names if n.startswith("fused/")]
    plain_grads = [n for n in names if n.startswith("grad/")]
    assert fused, f"no fused bucket declared: {names}"
    assert len(fused) + len(plain_grads) < 6, names


def test_min_compress_gate_survives_fusion(ps_env):
    """Compression on, every leaf below min_compress_bytes: the fused
    buckets must stay on the DENSE path (full precision), so the result
    matches the uncompressed run exactly — the gate's tensors must not
    be quantized via the fused key."""
    cfg, params, batch = _mlp_setup()
    dense, _ = _run_steps(ps_env, params, batch, cfg)
    got, _ = _run_steps(
        ps_env, params, batch, cfg,
        compression={"compressor": "onebit", "ef": "vanilla"},
        min_compress_bytes=1 << 30)
    for a, b in zip(dense, got):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-7)


@pytest.mark.parametrize("kwargs", [
    {"compressor": "onebit", "ef": "vanilla"},
    # sparse codecs run at test-friendly k: at k=5% a run this short
    # touches each coordinate only a handful of times (EF or not),
    # which tests patience, not the wire
    {"compressor": "topk", "k": "0.25", "ef": "vanilla"},
    {"compressor": "randomk", "k": "0.25", "ef": "vanilla"},
    {"compressor": "dithering", "s": "127"},
], ids=["onebit", "topk", "randomk", "dithering"])
def test_every_codec_trains_over_ps(ps_env, kwargs):
    """Per-codec end-to-end PS training (the reference's test_onebit /
    test_topk / test_randomk / test_dithering pattern: real wire, real
    server mirror, EF where the codec is biased): loss must decrease
    through the host codec tier — which routes onebit/topk/randomk via
    the native C ABI codec when available."""
    import jax
    import jax.numpy as jnp
    from byteps_tpu.core.state import get_state
    from byteps_tpu.jax.train import make_ps_train_step
    from byteps_tpu.models import mlp

    cfg, params, _ = _mlp_setup()
    # LEARNABLE synthetic task (labels from a linear map, the
    # test_train.synthetic_classification shape) — random labels have a
    # loss floor that masks whether the compressed gradient works
    rng = np.random.RandomState(0)
    x = rng.randn(256, 64).astype(np.float32)
    w = rng.randn(64, 10).astype(np.float32)
    batch = {"x": jnp.asarray(x),
             "y": jnp.asarray(np.argmax(x @ w, -1), jnp.int32)}
    tx = optax.adam(3e-3)
    opt = tx.init(params)
    step = make_ps_train_step(
        lambda p, b: mlp.loss_fn(p, b, cfg), tx, get_state().mesh,
        compression=kwargs, min_compress_bytes=0, device_compress=False)
    losses = []
    for _ in range(60):
        params, opt, loss = step(params, opt, batch)
        losses.append(float(loss))
    assert np.isfinite(losses).all(), losses
    assert losses[-1] < losses[0] * 0.7, (kwargs, losses[0], losses[-1])
