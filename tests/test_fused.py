"""Fused PUSHPULL wire op + completion reactor (BYTEPS_FUSED_PUSHPULL,
native/ps.cc PUSHPULL + server/client.py zpushpull_async +
core/scheduler.py _do_wire).

Covers: bitwise parity of fused vs two-op results for dense,
fused-bucket, compressed (onebit) and rowsparse traffic; the
deterministic wire-efficiency proof (fused mode sends HALF the request
messages per round, via the ``wire/*`` counters — wall-clock on a
2-core box flakes, message counts don't); the reactor concurrency
proof (in-flight partitions exceed the pull-pool thread count against
a throttled loopback server); raw-client fused semantics (parked
fused replies across an aggregation round, error replies, poisoned
connections); and a slow mixed-traffic churn asserting no handle or
arena-lease leaks.
"""

import contextlib
import os
import threading
import time

import numpy as np
import pytest

from byteps_tpu.config import Config
from byteps_tpu.core.types import DataType, RequestType, get_command_type
from byteps_tpu.server import run_server
from byteps_tpu.server.client import PSClient

_PORT = [24900]

CMD_F32 = get_command_type(RequestType.DEFAULT_PUSH_PULL, DataType.FLOAT32)


def _start_server(num_workers=1, **cfgkw):
    port = _PORT[0]
    _PORT[0] += 1
    t = threading.Thread(
        target=run_server,
        args=(port, Config(num_workers=num_workers, num_servers=1,
                           **cfgkw)),
        daemon=True)
    t.start()
    return port, t


@contextlib.contextmanager
def _ps_env(extra_env: dict = None):
    """Loopback server + fresh bps.init, env restored on exit (the
    test_stream.py scaffolding)."""
    from byteps_tpu.core.state import GlobalState

    port = _PORT[0]
    _PORT[0] += 1
    env = {
        "DMLC_NUM_WORKER": "1", "DMLC_NUM_SERVER": "1",
        "DMLC_PS_ROOT_URI": "127.0.0.1", "DMLC_PS_ROOT_PORT": str(port),
        "BYTEPS_FORCE_DISTRIBUTED": "1", **(extra_env or {}),
    }
    saved = {k: os.environ.get(k) for k in env}
    os.environ.update(env)
    server = threading.Thread(
        target=run_server,
        args=(port, Config(num_workers=1, num_servers=1)), daemon=True)
    server.start()
    GlobalState._instance = None
    import byteps_tpu as bps
    bps.init()
    try:
        yield bps
    finally:
        bps.shutdown()
        server.join(timeout=10)
        GlobalState._instance = None
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


# --------------------------------------------------------------------- #
# raw client: fused op semantics
# --------------------------------------------------------------------- #


@pytest.mark.parametrize("ipc", ["1", "0"])
def test_fused_roundtrip_and_multiround(ipc, monkeypatch):
    # both transports: the shm-ring upgrade (loopback default) AND plain
    # TCP — the fused reply must park/stream identically on either
    monkeypatch.setenv("BYTEPS_ENABLE_IPC", ipc)
    port, t = _start_server()
    c = PSClient([f"127.0.0.1:{port}"], worker_id=0)
    assert (c.ipc_conns > 0) == (ipc == "1")
    x = np.arange(512, dtype=np.float32)
    c.init_key(0, 7, np.zeros_like(x), CMD_F32)
    out = np.empty(x.nbytes, np.uint8)
    for mult in (1.0, 2.0, 3.0):
        done = threading.Event()
        res = {}

        def cb(n, err, res=res, done=done):
            res["n"], res["err"] = n, err
            done.set()

        c.zpushpull_async(0, 7, x * mult, out, CMD_F32, cb)
        assert done.wait(15), "fused completion never fired"
        assert res["err"] is None and res["n"] == x.nbytes
        np.testing.assert_array_equal(out.view(np.float32), x * mult)
    c.close()
    t.join(timeout=10)
    assert not t.is_alive()


def test_fused_reply_parks_until_round_completes():
    """The server-side heart of the op: worker 0's fused reply is parked
    alongside parked pulls and streams back the moment worker 1's push
    completes the aggregation round — no second request leg."""
    port, t = _start_server(num_workers=2)
    c0 = PSClient([f"127.0.0.1:{port}"], worker_id=0)
    c1 = PSClient([f"127.0.0.1:{port}"], worker_id=1)
    x0 = np.full(64, 1.5, np.float32)
    x1 = np.full(64, 2.0, np.float32)
    t_init = threading.Thread(
        target=lambda: c1.init_key(0, 3, np.zeros_like(x1), CMD_F32))
    t_init.start()
    c0.init_key(0, 3, np.zeros_like(x0), CMD_F32)
    t_init.join(timeout=10)

    out0 = np.empty(x0.nbytes, np.uint8)
    done0 = threading.Event()
    c0.zpushpull_async(0, 3, x0, out0, CMD_F32,
                       lambda n, e: done0.set())
    time.sleep(0.3)
    assert not done0.is_set()          # parked: round incomplete
    c1.zpush(0, 3, x1, CMD_F32)        # completes the round
    assert done0.wait(timeout=10)
    np.testing.assert_allclose(out0.view(np.float32), x0 + x1)
    # worker 1 pulls the same aggregate the fused reply carried
    out1 = np.empty_like(x1)
    c1.zpull(0, 3, out1, CMD_F32)
    np.testing.assert_allclose(out1, x0 + x1)
    c0.close()
    c1.close()


def test_fused_error_reply_fails_ticket_cleanly():
    """A push-stage reject (length mismatch) error-replies the fused
    request; the callback gets the error and the connection stays
    usable (the error reply is in-band, not a poison)."""
    port, t = _start_server()
    c = PSClient([f"127.0.0.1:{port}"], worker_id=0)
    x = np.arange(64, dtype=np.float32)
    c.init_key(0, 9, np.zeros_like(x), CMD_F32)
    bad = np.zeros(7, np.float32)
    out = np.empty(x.nbytes, np.uint8)
    done = threading.Event()
    res = {}

    def cb(n, err):
        res["err"] = err
        done.set()

    c.zpushpull_async(0, 9, bad, out, CMD_F32, cb)
    assert done.wait(15)
    assert isinstance(res["err"], RuntimeError)
    # the connection survives: a correct fused round still works
    done2 = threading.Event()
    res2 = {}

    def cb2(n, err):
        res2["err"] = err
        done2.set()

    c.zpushpull_async(0, 9, x, out, CMD_F32, cb2)
    assert done2.wait(15)
    assert res2["err"] is None
    np.testing.assert_array_equal(out.view(np.float32), x)
    c.close()


def test_fused_close_with_inflight_resolves_callbacks():
    """Outstanding fused tickets at close() resolve with an error
    instead of leaking (the reactor drains the abort records before the
    native client is destroyed)."""
    port, t = _start_server(num_workers=2)  # round can never complete
    c = PSClient([f"127.0.0.1:{port}"], worker_id=0)
    c2 = PSClient([f"127.0.0.1:{port}"], worker_id=1)
    x = np.ones(32, np.float32)
    t_init = threading.Thread(
        target=lambda: c2.init_key(0, 4, np.zeros_like(x), CMD_F32))
    t_init.start()
    c.init_key(0, 4, np.zeros_like(x), CMD_F32)
    t_init.join(timeout=10)
    out = np.empty(x.nbytes, np.uint8)
    done = threading.Event()
    res = {}

    def cb(n, err):
        res["err"] = err
        done.set()

    c.zpushpull_async(0, 4, x, out, CMD_F32, cb)  # parks forever
    time.sleep(0.2)
    c.close(shutdown_servers=False)
    assert done.wait(10), "close() left the fused callback unresolved"
    assert res["err"] is not None
    c2.close(shutdown_servers=False)


# --------------------------------------------------------------------- #
# PSClient error-path hardening
# --------------------------------------------------------------------- #


def test_pull_rejects_noncontiguous_buffer():
    port, t = _start_server()
    c = PSClient([f"127.0.0.1:{port}"], worker_id=0)
    x = np.arange(64, dtype=np.float32)
    c.init_key(0, 5, np.zeros_like(x), CMD_F32)
    c.zpush(0, 5, x, CMD_F32)
    strided = np.empty((64, 2), np.float32)[:, 0]
    assert not strided.flags["C_CONTIGUOUS"]
    with pytest.raises(ValueError, match="C-contiguous"):
        c.zpull(0, 5, strided, CMD_F32)
    with pytest.raises(ValueError, match="C-contiguous"):
        c.zpushpull_async(0, 5, x, strided, CMD_F32, lambda n, e: None)
    # nothing was sent: the connection is not poisoned
    out = np.empty_like(x)
    c.zpull(0, 5, out, CMD_F32)
    np.testing.assert_array_equal(out, x)
    c.close()


def test_pull_reply_longer_than_view_raises_cleanly():
    """A reply larger than the output view is drained whole by the
    native side (the byte stream stays message-aligned) and reported as
    an error — NOT truncated into the buffer, and NOT a poisoned
    connection."""
    port, t = _start_server()
    c = PSClient([f"127.0.0.1:{port}"], worker_id=0)
    x = np.arange(128, dtype=np.float32)
    c.init_key(0, 6, np.zeros_like(x), CMD_F32)
    c.zpush(0, 6, x, CMD_F32)
    small = np.empty(32, np.float32)  # 128B view vs 512B reply
    with pytest.raises(RuntimeError, match="pull failed"):
        c.zpull(0, 6, small, CMD_F32)
    # connection survives: the full-size pull still answers
    out = np.empty_like(x)
    c.zpull(0, 6, out, CMD_F32, exact=True)
    np.testing.assert_array_equal(out, x)
    c.close()


def test_pull_reply_shorter_than_view_raises_with_exact():
    port, t = _start_server()
    c = PSClient([f"127.0.0.1:{port}"], worker_id=0)
    x = np.arange(16, dtype=np.float32)
    c.init_key(0, 8, np.zeros_like(x), CMD_F32)
    c.zpush(0, 8, x, CMD_F32)
    big = np.zeros(64, np.float32)  # 256B view vs 64B reply
    with pytest.raises(RuntimeError, match="expected exactly"):
        c.zpull(0, 8, big, CMD_F32, exact=True)
    # without exact, the caller opted into variable-length replies
    got = c.zpull(0, 8, big, CMD_F32)
    assert got == x.nbytes
    np.testing.assert_array_equal(big[:16], x)
    c.close()


def test_out_of_range_server_raises_before_wire():
    port, t = _start_server()
    c = PSClient([f"127.0.0.1:{port}"], worker_id=0)
    x = np.ones(8, np.float32)
    out = np.empty_like(x)
    for fn in (lambda: c.init_key(3, 1, x, CMD_F32),
               lambda: c.zpush(3, 1, x, CMD_F32),
               lambda: c.zpush_async(-1, 1, x, CMD_F32),
               lambda: c.zpull(3, 1, out, CMD_F32),
               lambda: c.comp_init(3, 1, "compressor=onebit;n=8"),
               lambda: c.zpushpull_async(3, 1, x, out, CMD_F32,
                                         lambda n, e: None)):
        with pytest.raises(ValueError, match="out of range"):
            fn()
    # the client is unharmed
    c.init_key(0, 1, np.zeros_like(x), CMD_F32)
    c.zpush(0, 1, x, CMD_F32)
    c.zpull(0, 1, out, CMD_F32)
    np.testing.assert_array_equal(out, x)
    c.close()


# --------------------------------------------------------------------- #
# scheduler: parity, wire-efficiency proof, reactor concurrency
# --------------------------------------------------------------------- #


def _dense_rounds(fused: str, rounds: int = 3, n_tensors: int = 4):
    """N rounds of dense push_pull_async under the given fused setting;
    returns (results, metrics snapshot)."""
    with _ps_env({"BYTEPS_FUSED_PUSHPULL": fused,
                  # two partitions per tensor: exercises partition fanout
                  "BYTEPS_PARTITION_BYTES": "8192",
                  "BYTEPS_FUSION_BYTES": "0"}) as bps:
        rng = np.random.RandomState(0)
        grads = [rng.randn(4096).astype(np.float32)
                 for _ in range(n_tensors)]
        results = []
        for r in range(rounds):
            hs = [bps.push_pull_async(g * (r + 1), f"t{i}", average=False)
                  for i, g in enumerate(grads)]
            results.append([np.array(bps.synchronize(h, timeout=60))
                            for h in hs])
        return results, bps.get_metrics()


def test_fused_dense_parity_and_half_requests():
    """Dense traffic: fused and two-op results are bitwise identical,
    and the DETERMINISTIC wire-efficiency proof — per round, fused mode
    sends HALF the request messages (1 fused vs push+pull per
    partition), asserted on the ``wire/*`` counters rather than
    wall-clock."""
    res_f, m_f = _dense_rounds("1")
    res_t, m_t = _dense_rounds("0")
    for a_round, b_round in zip(res_f, res_t):
        for a, b in zip(a_round, b_round):
            np.testing.assert_array_equal(a, b)
    cf, ct = m_f["counters"], m_t["counters"]
    # fused arm: every partition round trip rides ONE pushpull message
    assert cf["wire/pushpull_requests"] > 0
    assert cf["wire/push_requests"] == 0
    assert cf["wire/pull_requests"] == 0
    # two-op arm: one push AND one pull per partition per round
    assert ct["wire/pushpull_requests"] == 0
    assert ct["wire/push_requests"] == ct["wire/pull_requests"]
    assert ct["wire/push_requests"] == cf["wire/pushpull_requests"]
    fused_msgs = cf["wire/pushpull_requests"]
    twoop_msgs = ct["wire/push_requests"] + ct["wire/pull_requests"]
    assert fused_msgs * 2 == twoop_msgs
    # payload bytes match both ways (the fused op moves the same data)
    assert cf["wire/push_bytes"] == ct["wire/push_bytes"]
    assert cf["wire/pull_bytes"] == ct["wire/pull_bytes"]


@pytest.mark.parametrize("extra,prefix", [
    ({"BYTEPS_FUSION_BYTES": "4096"}, "bucket"),   # fused-bucket keys
])
def test_fused_bucket_parity(extra, prefix):
    """Small leaves riding a fused bucket produce identical results
    under fused and two-op wire modes."""
    def run(fused):
        with _ps_env({"BYTEPS_FUSED_PUSHPULL": fused, **extra}) as bps:
            rng = np.random.RandomState(1)
            smalls = [rng.randn(64).astype(np.float32) for _ in range(6)]
            outs = []
            for r in range(2):
                hs = [bps.push_pull_async(s + r, f"{prefix}{i}",
                                          average=False)
                      for i, s in enumerate(smalls)]
                outs.append([np.array(bps.synchronize(h, timeout=60))
                             for h in hs])
            return outs

    a, b = run("1"), run("0")
    for ra, rb in zip(a, b):
        for x, y in zip(ra, rb):
            np.testing.assert_array_equal(x, y)


def test_fused_compressed_parity():
    """Onebit host-codec traffic (COMPRESS → WIRE → DECOMPRESS under
    fused; COMPRESS → PUSH → PULL → DECOMPRESS under two-op) is bitwise
    identical — the fused reply is the same compressed-wire aggregate
    the two-op PULL fetches."""
    def run(fused):
        with _ps_env({"BYTEPS_FUSED_PUSHPULL": fused}) as bps:
            from byteps_tpu.core.state import get_state
            from byteps_tpu.server.compressed import CompressedRegistry

            state = get_state()
            reg = CompressedRegistry(state.ps_client, 1,
                                     {"compressor": "onebit"})
            rng = np.random.RandomState(2)
            g = rng.randn(300_000).astype(np.float32)
            outs = []
            for _ in range(3):
                h = reg.push_pull_async(state, "cg", g, average=False)
                outs.append(np.array(bps.synchronize(h, timeout=60)))
            return outs

    a, b = run("1"), run("0")
    for x, y in zip(a, b):
        np.testing.assert_array_equal(x, y)


def test_fused_rowsparse_parity():
    def run(fused):
        with _ps_env({"BYTEPS_FUSED_PUSHPULL": fused}) as bps:
            rng = np.random.RandomState(3)
            g = np.zeros((256, 32), np.float32)
            rows = rng.choice(256, 40, replace=False)
            g[rows] = rng.randn(40, 32)
            return [np.array(bps.push_pull_rowsparse(g * (r + 1), "emb",
                                                     average=False))
                    for r in range(3)]

    a, b = run("1"), run("0")
    for x, y in zip(a, b):
        np.testing.assert_array_equal(x, y)


def test_fused_inflight_exceeds_pull_pool(monkeypatch):
    """The reactor-model acceptance proof: against a throttled loopback
    server, fused mode sustains MORE in-flight partitions than the
    two-op pull pool has threads — in-flight is bounded by scheduling
    credit, not thread count. (Two-op mode structurally caps
    outstanding pulls at the pull-pool size: each one parks a thread.)"""
    from byteps_tpu.core.registry import TensorRegistry
    from byteps_tpu.core.scheduler import HandleManager, PipelineScheduler

    monkeypatch.setenv("BYTEPS_SERVER_THROTTLE_MBPS", "30")
    port, t = _start_server()
    n_threads = 2

    def peak(fused: str) -> int:
        monkeypatch.setenv("BYTEPS_FUSED_PUSHPULL", fused)
        c = PSClient([f"127.0.0.1:{port}"], worker_id=0)
        reg = TensorRegistry(Config(num_servers=1,
                                    partition_bytes=128 * 1024))
        ctx = reg.init_tensor(f"big{fused}", nbytes=16 * 128 * 1024,
                              dtype=DataType.FLOAT32)
        assert len(ctx.partitions) == 16
        sched = PipelineScheduler(c, num_threads=n_threads)
        try:
            x = np.random.RandomState(0).randn(
                16 * 128 * 1024 // 4).astype(np.float32)
            c.init_tensor(ctx, np.zeros_like(x))
            from byteps_tpu.core.scheduler import Handle
            hm = HandleManager()
            h = hm.allocate("big")
            sched.submit(ctx, x, h, average=False, num_workers=1)
            out = hm.wait_and_clear(h.id, timeout=120)
            np.testing.assert_array_equal(out, x)
            return c.inflight_peak
        finally:
            sched.stop()
            c.close(shutdown_servers=False)

    fused_peak = peak("1")
    twoop_peak = peak("0")
    assert twoop_peak <= n_threads, (
        f"two-op outstanding pulls exceeded the pool: {twoop_peak}")
    assert fused_peak > n_threads, (
        f"fused in-flight {fused_peak} did not exceed the old pull-pool "
        f"bound {n_threads}")
    # drain the throttled server
    PSClient([f"127.0.0.1:{port}"], worker_id=0).close()
    t.join(timeout=10)


# --------------------------------------------------------------------- #
# stress: mixed traffic churn, leak-free (slow)
# --------------------------------------------------------------------- #


@pytest.mark.slow
def test_fused_mixed_stress_no_leaks():
    """64+ partitions of mixed dense/compressed/rowsparse keys churned
    for many rounds with fused on: results bitwise-identical to the
    two-op path, and no handle or arena-lease leaks afterwards
    (bps.get_metrics() arena section + the handle table)."""
    def run(fused):
        with _ps_env({"BYTEPS_FUSED_PUSHPULL": fused,
                      "BYTEPS_PARTITION_BYTES": "16384",
                      "BYTEPS_FUSION_BYTES": "0"}) as bps:
            from byteps_tpu.core.state import get_state
            from byteps_tpu.server.compressed import CompressedRegistry

            state = get_state()
            rng = np.random.RandomState(7)
            # 10 dense tensors x 4 partitions + compressed + rowsparse:
            # >64 keys total in flight per round
            dense = [rng.randn(16384).astype(np.float32)
                     for _ in range(10)]
            comp = rng.randn(400_000).astype(np.float32)
            sparse = np.zeros((512, 16), np.float32)
            rows = rng.choice(512, 60, replace=False)
            sparse[rows] = rng.randn(60, 16)
            reg = CompressedRegistry(state.ps_client, 1,
                                     {"compressor": "onebit"})
            outs = []
            for r in range(12):
                hs = [bps.push_pull_async(g * (1 + 0.1 * r), f"d{i}",
                                          average=False)
                      for i, g in enumerate(dense)]
                hc = reg.push_pull_async(state, "c", comp, average=False)
                row = bps.push_pull_rowsparse(sparse, "emb",
                                              average=False)
                round_out = [np.array(bps.synchronize(h, timeout=120))
                             for h in hs]
                round_out.append(np.array(bps.synchronize(hc,
                                                          timeout=120)))
                round_out.append(np.array(row))
                outs.append(round_out)
            snap = bps.get_metrics()
            # no handle leaks: every synchronize cleared its handle
            assert not state.handles._handles, (
                f"leaked handles: {list(state.handles._handles)}")
            return outs, snap

    outs_f, snap_f = run("1")
    outs_t, _ = run("0")
    for ra, rb in zip(outs_f, outs_t):
        for a, b in zip(ra, rb):
            np.testing.assert_array_equal(a, b)
    arena = snap_f["arena"]
    # every checked-out lease came back: live slots are bounded by the
    # distinct staging keys (no per-round growth), and nothing is stuck
    # mid-checkout
    assert arena["slots_live"] <= arena["slot_allocs"]
    assert arena["allocs_avoided"] > 0  # steady state actually reused
    gauges = snap_f["gauges"]
    assert gauges.get("wire/inflight", 0) == 0  # all requests drained
