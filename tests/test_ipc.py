"""Colocated shm (IPC) transport tests.

The loopback PS tests already ride the shm transport implicitly (every
127.0.0.1 connection upgrades, tests/test_ps.py); these tests pin the
transport-specific contracts: the upgrade actually engages, the TCP
fallback works when disabled, both transports agree numerically, failure
detection still fires through the silent-TCP liveness signal, and the shm
segments are unlinked (no /dev/shm litter).

Reference: ps-lite's colocated IPC shortcut, enabled by BYTEPS_ENABLE_IPC
(docs/best-practice.md:32).
"""

import os
import threading

import numpy as np
import pytest

from byteps_tpu.config import Config
from byteps_tpu.core.types import DataType, RequestType, get_command_type
from byteps_tpu.server import run_server
from byteps_tpu.server.client import PSClient

from test_ps import start_servers

CMD_F32 = get_command_type(RequestType.DEFAULT_PUSH_PULL, DataType.FLOAT32)


def _shm_names():
    try:
        return {n for n in os.listdir("/dev/shm") if n.startswith("bps-ipc-")}
    except FileNotFoundError:
        return set()


def test_ipc_upgrade_engages_and_unlinks():
    before = _shm_names()
    addrs, threads = start_servers(1, num_workers=1)
    c = PSClient(addrs, worker_id=0)
    assert c.ipc_conns > 0  # loopback => every stripe conn upgrades
    # handshake unlinks the name immediately: nothing new in /dev/shm
    assert _shm_names() <= before
    x = np.arange(4096, dtype=np.float32)
    c.init_key(0, 3, np.zeros_like(x), CMD_F32)
    c.zpush(0, 3, x, CMD_F32)
    out = np.empty_like(x)
    c.zpull(0, 3, out, CMD_F32)
    np.testing.assert_array_equal(out, x)
    c.close()
    for t in threads:
        t.join(timeout=10)
        assert not t.is_alive()
    assert _shm_names() <= before


def test_ipc_disabled_falls_back_to_tcp(monkeypatch):
    monkeypatch.setenv("BYTEPS_ENABLE_IPC", "0")
    addrs, threads = start_servers(1, num_workers=1)
    c = PSClient(addrs, worker_id=0)
    assert c.ipc_conns == 0
    x = np.linspace(-1, 1, 1000).astype(np.float32)
    c.init_key(0, 5, np.zeros_like(x), CMD_F32)
    c.zpush(0, 5, x, CMD_F32)
    out = np.empty_like(x)
    c.zpull(0, 5, out, CMD_F32)
    np.testing.assert_array_equal(out, x)
    c.close()
    for t in threads:
        t.join(timeout=10)


def test_ipc_two_workers_sum_matches_tcp(monkeypatch):
    """Same 2-worker aggregation, once over shm and once over TCP: the
    transports must be numerically indistinguishable."""
    results = {}
    for label, env in (("ipc", None), ("tcp", "0")):
        if env is None:
            monkeypatch.delenv("BYTEPS_ENABLE_IPC", raising=False)
        else:
            monkeypatch.setenv("BYTEPS_ENABLE_IPC", env)
        addrs, threads = start_servers(1, num_workers=2)
        cs = [PSClient(addrs, worker_id=w) for w in range(2)]
        want_ipc = env is None
        assert all((c.ipc_conns > 0) == want_ipc for c in cs)
        rng = np.random.RandomState(7)
        xs = [rng.randn(8192).astype(np.float32) for _ in range(2)]
        # init blocks until BOTH workers' init pushes arrive: parallel
        its = [threading.Thread(
            target=lambda c=c: c.init_key(0, 11, np.zeros_like(xs[0]),
                                          CMD_F32)) for c in cs]
        for t in its:
            t.start()
        for t in its:
            t.join(timeout=60)
        outs = [np.empty_like(xs[0]) for _ in range(2)]

        def round_trip(w):
            cs[w].zpush(0, 11, xs[w], CMD_F32)
            cs[w].zpull(0, 11, outs[w], CMD_F32)

        ts = [threading.Thread(target=round_trip, args=(w,))
              for w in range(2)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=60)
        results[label] = outs[0].copy()
        np.testing.assert_array_equal(outs[0], outs[1])
        for c in cs:
            c.close()
        for t in threads:
            t.join(timeout=10)
    np.testing.assert_array_equal(results["ipc"], results["tcp"])


def test_ipc_large_message_exceeds_ring(monkeypatch):
    """Messages larger than the ring stream through in chunks (byte-stream
    semantics, not datagram): a 1MB payload over a 64KB ring."""
    monkeypatch.setenv("BYTEPS_IPC_RING_BYTES", str(64 << 10))
    addrs, threads = start_servers(1, num_workers=1)
    c = PSClient(addrs, worker_id=0)
    assert c.ipc_conns > 0
    x = np.random.RandomState(0).randn(1 << 18).astype(np.float32)  # 1MB
    c.init_key(0, 21, np.zeros_like(x), CMD_F32)
    c.zpush(0, 21, x, CMD_F32)
    out = np.empty_like(x)
    c.zpull(0, 21, out, CMD_F32)
    np.testing.assert_array_equal(out, x)
    c.close()
    for t in threads:
        t.join(timeout=10)


def test_ipc_failure_detection_still_fires():
    """Worker death must still be observed through the silent TCP fd: a
    surviving worker's parked pull errors out instead of wedging."""
    addrs, threads = start_servers(1, num_workers=2)
    c0 = PSClient(addrs, worker_id=0)
    c1 = PSClient(addrs, worker_id=1)
    assert c0.ipc_conns > 0 and c1.ipc_conns > 0
    x = np.ones(1024, np.float32)

    def init(c):
        c.init_key(0, 31, np.zeros_like(x), CMD_F32)

    t0 = threading.Thread(target=init, args=(c0,))
    t1 = threading.Thread(target=init, args=(c1,))
    t0.start(); t1.start(); t0.join(30); t1.join(30)

    c0.zpush(0, 31, x, CMD_F32)
    err = []

    def pull():
        out = np.empty_like(x)
        try:
            c0.zpull(0, 31, out, CMD_F32)  # parks: worker 1 never pushes
        except RuntimeError as e:
            err.append(e)

    t = threading.Thread(target=pull)
    t.start()
    import time
    time.sleep(0.3)
    c1.close(shutdown_servers=False)  # die without SHUTDOWN
    t.join(timeout=30)
    assert not t.is_alive() and err, "parked pull must fail fast"
    c0.close()
    for th in threads:
        th.join(timeout=10)
