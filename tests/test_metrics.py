"""Unified metrics registry + per-step pipeline profiler
(core/metrics.py): counter/gauge/histogram correctness under concurrent
writers, StepReport assembly for a real make_ps_train_step step (stream
export on and off), Prometheus text exposition, the stall-detector
classification on synthetic PULL-bound vs COMPUTE-bound reports, the
docs-schema liveness guard, the frozen-registry (BYTEPS_METRICS=0)
behavior, and the MetricAverageCallback shared-deadline fix."""

import contextlib
import os
import re
import threading

import numpy as np
import optax
import pytest

from byteps_tpu.config import Config
from byteps_tpu.core.metrics import (
    Histogram, MetricsRegistry, StepProfiler, StepReport, classify_step,
    prometheus_text,
)
from byteps_tpu.server import run_server

_PORT = [24100]


# --------------------------------------------------------------------- #
# unit tier: instruments under concurrent writers
# --------------------------------------------------------------------- #


def _hammer(n_threads, fn):
    threads = [threading.Thread(target=fn) for _ in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()


def test_counter_concurrent_writers_exact():
    reg = MetricsRegistry()
    c = reg.counter("c")
    _hammer(8, lambda: [c.inc() for _ in range(5000)])
    assert c.value == 8 * 5000


def test_histogram_concurrent_writers_consistent():
    reg = MetricsRegistry()
    h = reg.histogram("h")
    _hammer(8, lambda: [h.record(v) for v in (3, 100, 5000, 1 << 20)])
    s = h.snapshot()
    assert s["count"] == 8 * 4
    assert sum(s["buckets"]) == s["count"]
    assert s["min"] == 3 and s["max"] == 1 << 20
    assert s["sum"] == 8 * (3 + 100 + 5000 + (1 << 20))


def test_gauge_set_max_and_lazy_fn():
    reg = MetricsRegistry()
    g = reg.gauge("g")
    g.set_max(5)
    g.set_max(3)
    assert g.value == 5
    lazy = reg.gauge("lazy")
    lazy.set_fn(lambda: 42)
    assert lazy.value == 42
    assert reg.snapshot()["gauges"]["lazy"] == 42


def test_histogram_percentiles_log2_bounds():
    h = Histogram("h")
    for _ in range(99):
        h.record(10)     # bucket 4, upper bound 15
    h.record(100000)     # bucket 17, upper bound 131071
    assert h.percentile(0.5) == 15.0
    assert h.percentile(0.99) == 15.0
    s = h.snapshot()
    assert s["p50"] == 15.0
    assert s["p99"] == 15.0
    # the outlier decides the extreme tail (100000 -> bucket 17)
    assert h.percentile(1.0) == (1 << 17) - 1


def test_registry_get_or_create_is_stable():
    reg = MetricsRegistry()
    assert reg.counter("x") is reg.counter("x")
    assert reg.histogram("y") is reg.histogram("y")
    assert reg.gauge("z") is reg.gauge("z")


def test_disabled_registry_freezes_instruments():
    reg = MetricsRegistry(enabled=False)
    c = reg.counter("c")
    h = reg.histogram("h")
    g = reg.gauge("g")
    c.inc(10)
    h.record(100)
    g.set(5)
    assert c.value == 0
    assert h.snapshot()["count"] == 0
    assert g.value == 0
    # the snapshot surface itself still works
    snap = reg.snapshot()
    assert snap["enabled"] is False and "counters" in snap


# --------------------------------------------------------------------- #
# Prometheus exposition
# --------------------------------------------------------------------- #


def test_prometheus_text_format():
    reg = MetricsRegistry()
    reg.counter("wire/push_bytes").inc(128)
    reg.gauge("scheduler/queue_depth").set(5)
    h = reg.histogram("scheduler/pull_us/dense")
    h.record(12000)
    h.record(41000)
    reg.section("arena", lambda: {"slots_live": 3, "enabled": True})
    txt = prometheus_text(reg)
    assert "# TYPE byteps_wire_push_bytes counter\n" \
           "byteps_wire_push_bytes 128" in txt
    assert "# TYPE byteps_scheduler_queue_depth gauge" in txt
    assert "# TYPE byteps_scheduler_pull_us_dense histogram" in txt
    # cumulative buckets end at +Inf == count
    assert 'byteps_scheduler_pull_us_dense_bucket{le="+Inf"} 2' in txt
    assert "byteps_scheduler_pull_us_dense_count 2" in txt
    assert "byteps_scheduler_pull_us_dense_sum 53000" in txt
    # sections flatten to gauges; bools become 0/1
    assert "byteps_arena_slots_live 3" in txt
    assert "byteps_arena_enabled 1" in txt
    # every non-comment line is "name value" with a sane metric name
    for line in txt.strip().splitlines():
        if line.startswith("#"):
            continue
        name, _, value = line.partition(" ")
        assert re.fullmatch(r"[a-zA-Z_][a-zA-Z0-9_]*(\{[^}]*\})?", name), line
        float(value)


def test_prometheus_http_endpoint():
    import json
    import urllib.request

    from byteps_tpu.core.metrics import start_http_server

    reg = MetricsRegistry()
    reg.counter("c").inc(7)
    srv = start_http_server(reg, 0)  # ephemeral port
    try:
        port = srv.server_address[1]
        txt = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=10).read().decode()
        assert "byteps_c 7" in txt
        snap = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{port}/", timeout=10).read().decode())
        assert snap["counters"]["c"] == 7
    finally:
        srv.shutdown()
        srv.server_close()


# --------------------------------------------------------------------- #
# stall detector
# --------------------------------------------------------------------- #


def test_classify_pull_bound():
    r = StepReport(step=1, wall_ms=60, compute_ms=12.0, pull_p95_ms=41.0,
                   push_p95_ms=2.0, queue_depth_peak=37)
    msg = classify_step(r)
    assert msg.startswith("PULL-bound")
    assert "pull p95 41.0ms" in msg and "compute 12.0ms" in msg
    assert "queue depth peaked 37" in msg


def test_classify_compute_bound():
    r = StepReport(step=2, wall_ms=60, compute_ms=50.0, pull_p95_ms=4.0,
                   push_p95_ms=2.0)
    msg = classify_step(r)
    assert msg.startswith("COMPUTE-bound")
    assert "compute wall 50.0ms" in msg


def test_classify_push_and_update_bound():
    assert classify_step(StepReport(
        compute_ms=1.0, push_p95_ms=30.0)).startswith("PUSH-bound")
    assert classify_step(StepReport(
        compute_ms=1.0, h2d_update_p95_ms=9.0)).startswith("UPDATE-bound")


def test_profiler_ring_and_stall_counters():
    p = StepProfiler(window=2)
    for i in range(3):
        b = p.begin_step()
        b.stage_sample("PULL", 0.010 * (i + 1))
        b.queue_depth(i)
        b.credit_stall()
        b.mark("export_done")
        b.mark("drain_done")
        p.end_step(b, ttfp_ms=1.0, streamed=1, fallback=2)
    reports = p.reports()
    assert len(reports) == 2, "ring must cap at the window"
    assert [r.step for r in reports] == [2, 3]
    last = reports[-1]
    assert last.credit_stalls == 1 and last.queue_depth_peak == 2
    assert last.pull_p95_ms == pytest.approx(30.0, rel=0.01)
    snap = p.snapshot()
    assert snap["count"] == 2 and snap["last"]["step"] == 3
    assert "last_diagnosis" in snap


def test_profiler_disabled_returns_none():
    p = StepProfiler(enabled=False)
    assert p.begin_step() is None
    assert p.end_step(None) is None
    assert p.reports() == []


# --------------------------------------------------------------------- #
# integration tier: a real PS train step feeds the whole plane
# --------------------------------------------------------------------- #


@contextlib.contextmanager
def _ps_env(extra_env: dict = None):
    from byteps_tpu.core.state import GlobalState

    port = _PORT[0]
    _PORT[0] += 1
    env = {
        "DMLC_NUM_WORKER": "1", "DMLC_NUM_SERVER": "1",
        "DMLC_PS_ROOT_URI": "127.0.0.1", "DMLC_PS_ROOT_PORT": str(port),
        "BYTEPS_FORCE_DISTRIBUTED": "1", **(extra_env or {}),
    }
    saved = {k: os.environ.get(k) for k in env}
    os.environ.update(env)
    server = threading.Thread(
        target=run_server,
        args=(port, Config(num_workers=1, num_servers=1)), daemon=True)
    server.start()
    GlobalState._instance = None
    import byteps_tpu as bps
    bps.init()
    try:
        yield bps
    finally:
        bps.shutdown()
        server.join(timeout=10)
        GlobalState._instance = None
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def _train_rounds(steps=3, **kw):
    import jax
    import jax.numpy as jnp

    from byteps_tpu.core.state import get_state
    from byteps_tpu.jax.train import make_ps_train_step
    from byteps_tpu.models import mlp

    cfg = mlp.MLPConfig(in_dim=64, hidden=(48, 32), n_classes=10)
    params = mlp.init_params(jax.random.PRNGKey(0), cfg)
    rng = np.random.RandomState(0)
    batch = {"x": jnp.asarray(rng.rand(32, 64), jnp.float32),
             "y": jnp.asarray(rng.randint(0, 10, 32), jnp.int32)}
    tx = optax.adam(1e-2)
    opt = tx.init(params)
    step = make_ps_train_step(lambda p, b: mlp.loss_fn(p, b, cfg), tx,
                              get_state().mesh, **kw)
    for _ in range(steps):
        params, opt, loss = step(params, opt, batch)
    return float(loss)


@pytest.mark.parametrize("stream", [True, False])
def test_step_report_assembly_real_step(stream):
    # fusion off so leaves ride their own keys (streaming eligible);
    # the stream=False arm proves the report shape is identical when
    # every leaf exports through the post-jit fallback loop
    with _ps_env({"BYTEPS_FUSION_BYTES": "0"}) as bps:
        _train_rounds(steps=3, stream_export=stream)
        m = bps.get_metrics()
        steps = m["steps"]
        assert steps["count"] == 3
        last = steps["last"]
        assert last["step"] == 3
        assert last["wall_ms"] > 0
        assert last["compute_ms"] > 0
        assert last["drain_ms"] >= 0
        assert last["ttfp_ms"] is not None and last["ttfp_ms"] > 0
        total = last["streamed_leaves"] + last["fallback_leaves"]
        assert total == 6  # mlp: 3 layers x (w, b)
        if stream:
            assert last["streamed_leaves"] > 0
        else:
            assert last["streamed_leaves"] == 0
        # the scheduler fed per-stage samples for this step
        assert last["pull_p95_ms"] is not None
        assert last["push_p95_ms"] is not None
        assert last["queue_depth_peak"] >= 1
        assert "last_diagnosis" in steps and "-bound" in \
            steps["last_diagnosis"]
        # wire layer counted the traffic (fused default: one PUSHPULL
        # message per partition round trip instead of a push+pull pair)
        assert (m["counters"]["wire/push_requests"]
                + m["counters"]["wire/pushpull_requests"]) > 0
        assert m["counters"]["wire/pull_bytes"] > 0
        assert m["counters"]["wire/errors"] == 0
        # registry byte total mirrors the telemetry surface
        assert m["counters"]["pushpull/bytes_total"] > 0
        # per-stage histograms populated for the dense class
        assert m["histograms"]["scheduler/pull_us/dense"]["count"] > 0
        assert m["histograms"]["step/h2d_update_us"]["count"] > 0
        # reports surface, oldest first
        reports = bps.get_step_reports()
        assert [r["step"] for r in reports] == [1, 2, 3]


def test_metrics_off_freezes_but_snapshot_works():
    with _ps_env({"BYTEPS_METRICS": "0"}) as bps:
        _train_rounds(steps=2)
        m = bps.get_metrics()
        assert m["enabled"] is False
        assert m["steps"]["count"] == 0, "profiler must not assemble"
        assert m["counters"].get("wire/push_requests", 0) == 0
        # the deprecated alias still reads the live arena counters
        assert bps.get_arena_stats()["slots_live"] >= 0


def test_arena_stats_alias_matches_metrics_section():
    with _ps_env() as bps:
        _train_rounds(steps=2)
        alias = bps.get_arena_stats()
        section = bps.get_metrics()["arena"]
        assert alias == section


def test_compression_ratio_counters():
    with _ps_env() as bps:
        _train_rounds(steps=2, compression={"compressor": "onebit"},
                      min_compress_bytes=1, device_compress=False,
                      stream_export=False)
        m = bps.get_metrics()
        pre = m["counters"]["compress/bytes_pre"]
        post = m["counters"]["compress/bytes_post"]
        assert pre > 0 and 0 < post < pre, (pre, post)
        assert m["histograms"][
            "scheduler/compress_us/compressed"]["count"] > 0


def test_metrics_port_serves_through_init_lifecycle():
    import urllib.request

    from byteps_tpu.utils.net import free_port

    port = free_port()
    with _ps_env({"BYTEPS_METRICS_PORT": str(port)}) as bps:
        _train_rounds(steps=1)
        txt = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=10).read().decode()
        assert "byteps_wire_push_requests" in txt
    # shutdown() stopped the server
    with pytest.raises(Exception):
        urllib.request.urlopen(f"http://127.0.0.1:{port}/metrics",
                               timeout=2)


# --------------------------------------------------------------------- #
# docs-schema liveness guard (the docs can't rot silently)
# --------------------------------------------------------------------- #


def _documented_schema():
    doc = os.path.join(os.path.dirname(__file__), "..", "docs",
                       "observability.md")
    with open(doc) as f:
        text = f.read()
    m = re.search(r"```schema\n(.*?)```", text, re.S)
    assert m, "docs/observability.md lost its ```schema block"
    return [ln.strip() for ln in m.group(1).splitlines() if ln.strip()]


def _resolve(snap, path):
    parts = path.split(".")
    cur = snap
    for i, p in enumerate(parts):
        if isinstance(cur, dict) and p in cur:
            cur = cur[p]
            continue
        rest = ".".join(parts[i:])
        assert isinstance(cur, dict) and rest in cur, \
            f"documented key {path!r} missing from get_metrics()"
        return cur[rest]
    return cur


def test_documented_schema_is_live():
    keys = _documented_schema()
    assert len(keys) > 30, "schema block suspiciously small"
    with _ps_env() as bps:
        _train_rounds(steps=2, stream_export=False)
        snap = bps.get_metrics()
        for path in keys:
            _resolve(snap, path)


# --------------------------------------------------------------------- #
# MetricAverageCallback shared deadline (satellite fix)
# --------------------------------------------------------------------- #


def test_metric_average_shared_deadline(bps, monkeypatch):
    """The PS-tier drain must spend ONE shared BYTEPS_METRIC_TIMEOUT_S
    across all metrics, not a full timeout each: each synchronize gets
    the REMAINING time, so the captured timeouts strictly decrease."""
    import time

    import byteps_tpu as bps_mod
    from byteps_tpu import callbacks as cbs
    from byteps_tpu.core.state import get_state

    monkeypatch.setattr(get_state(), "scheduler", object())
    monkeypatch.setenv("BYTEPS_METRIC_TIMEOUT_S", "5")
    handles = iter(range(100))
    monkeypatch.setattr(bps_mod, "push_pull_async",
                        lambda v, name, average=True: next(handles))
    seen = []

    def fake_sync(h, timeout=None):
        seen.append(timeout)
        time.sleep(0.05)  # each wait consumes shared budget
        return np.asarray([2.0], np.float32)

    monkeypatch.setattr(bps_mod, "synchronize", fake_sync)
    state = {"metrics": {"a": 1.0, "b": 2.0, "c": 3.0}}
    cbs.MetricAverageCallback().on_epoch_end(0, state)
    assert state["metrics"] == {"a": 2.0, "b": 2.0, "c": 2.0}
    assert len(seen) == 3
    assert all(t is not None and t <= 5.0 for t in seen)
    assert seen[0] > seen[1] > seen[2], \
        f"timeouts must shrink toward the shared deadline: {seen}"
