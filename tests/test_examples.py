"""Examples are part of the product surface (the reference ships its
example/ scripts as the de-facto benchmark + system tests, SURVEY §4):
smoke them as real subprocesses the way a user runs them, pinned to the
CPU platform (a child inherits neither conftest's config updates nor a
usable TPU on CI)."""

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# the axon plugin initializes (and can hang) regardless of JAX_PLATFORMS;
# config.update is the reliable pin, run before the script. The script
# path + its args arrive as real argv (no string templating).
_PIN = ("from byteps_tpu.utils.jax_compat import force_cpu; force_cpu(8); "
        "import runpy, sys; sys.argv = sys.argv[1:]; "
        "runpy.run_path(sys.argv[0], run_name='__main__')")


def _run_example(name: str, argv: list, timeout: int = 420):
    path = os.path.join(REPO, "examples", name)
    return subprocess.run(
        [sys.executable, "-c", _PIN, path, *argv], cwd=REPO,
        capture_output=True, text=True, timeout=timeout,
        env={**os.environ, "PYTHONPATH":
             REPO + os.pathsep + os.environ.get("PYTHONPATH", "")})


def test_llama_pretrain_tiny_runs():
    r = _run_example("llama_pretrain.py",
                     ["--size", "tiny", "--steps", "3", "--batch", "8"])
    assert r.returncode == 0, r.stdout[-2000:] + r.stderr[-2000:]


def test_llama_pretrain_fsdp_tp():
    r = _run_example("llama_pretrain.py",
                     ["--size", "tiny", "--steps", "2", "--batch", "4",
                      "--fsdp", "--tp", "2"])
    assert r.returncode == 0, r.stdout[-2000:] + r.stderr[-2000:]
    # the inert combination must refuse, not silently un-shard
    r = _run_example("llama_pretrain.py",
                     ["--steps", "1", "--fsdp", "--ps"])
    assert r.returncode != 0
    assert "mutually exclusive" in r.stdout + r.stderr


def test_llama_pretrain_health_assert():
    """The dryrun numerics gate (docs/observability.md): a clean tiny
    PS run under --health-assert exits zero naming the verdict, and a
    run whose code path can never collect (no PS) FAILS loudly instead
    of passing vacuously — a gate that cannot fail is no gate."""
    import socket

    # negative first (cheap): without --ps the plane never observes a
    # gradient round — the engaged-proof must refuse the clean verdict
    r = _run_example("llama_pretrain.py",
                     ["--size", "tiny", "--steps", "1", "--batch", "4",
                      "--health-assert"])
    assert r.returncode != 0
    assert "never observed a gradient round" in r.stdout + r.stderr
    # positive: loopback PS (server subprocess + worker example run)
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    env = {**os.environ,
           "PYTHONPATH": REPO + os.pathsep
           + os.environ.get("PYTHONPATH", ""),
           "DMLC_NUM_WORKER": "1", "DMLC_NUM_SERVER": "1",
           "DMLC_PS_ROOT_URI": "127.0.0.1",
           "DMLC_PS_ROOT_PORT": str(port),
           "BYTEPS_FORCE_DISTRIBUTED": "1"}
    srv = subprocess.Popen(
        [sys.executable, "-c",
         "import sys; sys.path.insert(0, %r); "
         "from byteps_tpu.config import Config; "
         "from byteps_tpu.server import run_server; "
         "run_server(%d, Config(num_workers=1, num_servers=1))"
         % (REPO, port)],
        cwd=REPO, env=env)
    try:
        r = subprocess.run(
            [sys.executable, "-c", _PIN,
             os.path.join(REPO, "examples", "llama_pretrain.py"),
             "--size", "tiny", "--steps", "2", "--batch", "4", "--ps",
             "--health-assert"],
            cwd=REPO, capture_output=True, text=True, timeout=420,
            env=env)
        assert r.returncode == 0, r.stdout[-2000:] + r.stderr[-2000:]
        assert "health assert: no anomaly events" in r.stdout
        srv.wait(timeout=30)  # worker shutdown stops the server
    finally:
        if srv.poll() is None:
            srv.kill()


def test_train_mnist_runs():
    r = _run_example("train_mnist.py", ["--epochs", "1",
                                        "--batch-size", "64"])
    assert r.returncode == 0, r.stdout[-2000:] + r.stderr[-2000:]
    assert "accuracy" in r.stdout.lower() or "loss" in r.stdout.lower(), \
        r.stdout[-500:]


def test_tf_train_runs():
    r = _run_example("tf_train.py", [])
    assert r.returncode == 0, r.stdout[-2000:] + r.stderr[-2000:]
    assert "final loss" in r.stdout


@pytest.mark.slow  # >30s: tier-1 headroom (runs in the full suite)
def test_torch_train_all_frontends():
    """The torch-adapter example family (reference train_mnist_byteps +
    benchmark_byteps_ddp + benchmark_cross_barrier_byteps in one script):
    all three frontends run and report a final loss."""
    for fe in ("optimizer", "ddp", "cross_barrier"):
        r = _run_example("torch_train.py", ["--frontend", fe,
                                            "--steps", "6"])
        assert r.returncode == 0, (fe, r.stdout[-2000:] + r.stderr[-2000:])
        assert "final loss" in r.stdout, (fe, r.stdout[-500:])


def _run_example_over_ps(name: str, argv: list, extra_env: dict = None):
    """Run one example through a REAL loopback PS: DMLC env + a server
    subprocess whose lifetime brackets the run (worker shutdown stops
    it). Shared by every adapter-over-PS example test."""
    from byteps_tpu.utils.net import free_port

    port = free_port()
    env = {**os.environ,
           "DMLC_NUM_WORKER": "1", "DMLC_NUM_SERVER": "1",
           "DMLC_PS_ROOT_URI": "127.0.0.1",
           "DMLC_PS_ROOT_PORT": str(port),
           "BYTEPS_FORCE_DISTRIBUTED": "1",
           "PYTHONPATH": REPO + os.pathsep
           + os.environ.get("PYTHONPATH", ""),
           **(extra_env or {})}
    srv = subprocess.Popen(
        [sys.executable, "-m", "byteps_tpu.server"],
        env={**env, "JAX_PLATFORMS": "cpu"}, cwd=REPO,
        stdout=subprocess.DEVNULL, stderr=subprocess.STDOUT)
    try:
        path = os.path.join(REPO, "examples", name)
        r = subprocess.run(
            [sys.executable, "-c", _PIN, path, *argv],
            cwd=REPO, capture_output=True, text=True, timeout=420,
            env=env)
        if r.returncode == 0:
            srv.wait(timeout=30)  # worker shutdown stops the server
        return r
    finally:
        if srv.poll() is None:
            srv.kill()


@pytest.mark.slow  # >30s: tier-1 headroom (runs in the full suite)
def test_torch_train_distributed_ps():
    """The torch example through the loopback PS: this is where
    CrossBarrier's poller/drain path and the DistributedOptimizer's PS
    submits actually execute — the single-worker run above never enters
    them."""
    for fe in ("optimizer", "cross_barrier"):
        r = _run_example_over_ps("torch_train.py",
                                 ["--frontend", fe, "--steps", "6"])
        assert r.returncode == 0, \
            (fe, r.stdout[-2000:] + r.stderr[-2000:])
        assert "final loss" in r.stdout, (fe, r.stdout[-500:])


@pytest.mark.slow  # >30s: tier-1 headroom (runs in the full suite)
def test_benchmark_model_zoo_tiny():
    """examples/benchmark.py --tiny across the model zoo (the reference's
    benchmark vehicle covers its zoo the same way); bert has a dedicated
    smoke in test_bert_ps.py — this covers the rest."""
    for model in ("mlp", "resnet50", "vgg16", "moe", "llama"):
        r = _run_example(
            "benchmark.py",
            ["--model", model, "--tiny", "--num-iters", "1",
             "--num-warmup-batches", "1", "--num-batches-per-iter", "2",
             "--batch-size", "8"])
        assert r.returncode == 0, \
            (model, r.stdout[-2000:] + r.stderr[-2000:])
        assert "img/sec" in r.stdout, (model, r.stdout[-500:])


@pytest.mark.slow  # >30s: tier-1 headroom (runs in the full suite)
def test_tf1_train_runs():
    """The v1 Session example (MonitoredTrainingSession + broadcast hook
    + v1 DistributedOptimizer) trains."""
    r = _run_example("tf1_train.py", ["--steps", "30"])
    assert r.returncode == 0, r.stdout[-2000:] + r.stderr[-2000:]
    assert "final loss" in r.stdout, r.stdout[-500:]


def _first_and_final_loss(stdout: str):
    import re
    first = re.search(r"step\s+0 loss ([\d.]+)", stdout)
    final = re.search(r"final loss ([\d.]+)", stdout)
    assert first and final, stdout[-500:]
    return float(first.group(1)), float(final.group(1))


def test_mxnet_train_runs():
    """The mxnet-adapter example family (reference train_mnist_byteps +
    train_gluon_mnist_byteps): both frontends run (against the NDArray
    shim — mxnet is not in the image) and loss descends."""
    for fe in ("trainer", "optimizer"):
        r = _run_example("mxnet_train.py", ["--frontend", fe,
                                            "--steps", "15"])
        assert r.returncode == 0, (fe, r.stdout[-2000:] + r.stderr[-2000:])
        first, final = _first_and_final_loss(r.stdout)
        assert final < first, (fe, r.stdout[-500:])


def test_mxnet_train_compressed_ps():
    """The gluon trainer example through a REAL loopback PS with the
    onebit codec — the compression_params path only engages when a PS is
    configured."""
    r = _run_example_over_ps(
        "mxnet_train.py", ["--compression", "onebit", "--steps", "10"],
        extra_env={"BYTEPS_MIN_COMPRESS_BYTES": "0"})
    assert r.returncode == 0, r.stdout[-2000:] + r.stderr[-2000:]
    first, final = _first_and_final_loss(r.stdout)
    assert final < first, r.stdout[-500:]
