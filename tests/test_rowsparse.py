"""Row-sparse push_pull: the reference reserves kRowSparsePushPull
(common.h:267-271, server.h:39-41) but never implements it; here it is a
real op — workers push only the nonzero rows of embedding-style gradients,
the server scatter-adds into the dense store, pulls return the dense
aggregate."""

import threading

import numpy as np
import pytest

from byteps_tpu.config import Config
from byteps_tpu.core.registry import TensorRegistry
from byteps_tpu.core.types import DataType, RequestType, get_command_type
from byteps_tpu.server import run_server
from byteps_tpu.server.client import PSClient

_PORT = [27400]


def _server(num_workers, **cfgkw):
    port = _PORT[0]
    _PORT[0] += 1
    t = threading.Thread(
        target=run_server,
        args=(port, Config(num_workers=num_workers, num_servers=1, **cfgkw)),
        daemon=True)
    t.start()
    return port, t


def _ctx(name, rows, width, num_workers, partition_bytes=None):
    kw = dict(num_workers=num_workers, num_servers=1)
    if partition_bytes:
        kw["partition_bytes"] = partition_bytes
    reg = TensorRegistry(Config(**kw))
    return reg.init_tensor(name, rows * width * 4, DataType.FLOAT32,
                           align_bytes=width * 4)


def _sparse_grad(rng, rows, width, nnz):
    g = np.zeros((rows, width), np.float32)
    ids = rng.choice(rows, nnz, replace=False)
    g[ids] = rng.randn(nnz, width).astype(np.float32)
    return g


def test_two_workers_sparse_sum():
    rows, width = 64, 16
    port, t = _server(2)
    addr = [f"127.0.0.1:{port}"]
    c0, c1 = PSClient(addr, worker_id=0), PSClient(addr, worker_id=1)
    ctx0 = _ctx("emb", rows, width, 2)
    ctx1 = _ctx("emb", rows, width, 2)
    rng = np.random.RandomState(0)
    g0 = _sparse_grad(rng, rows, width, 7)
    g1 = _sparse_grad(rng, rows, width, 9)   # overlapping rows likely
    res = {}

    def w(c, ctx, g, tag):
        res[tag] = c.push_pull_rowsparse(ctx, g, average=False,
                                         num_workers=2)

    th = threading.Thread(target=w, args=(c1, ctx1, g1, "w1"), daemon=True)
    th.start()
    w(c0, ctx0, g0, "w0")
    th.join(timeout=30)
    assert not th.is_alive()
    want = g0 + g1
    np.testing.assert_allclose(res["w0"], want, rtol=1e-6)
    np.testing.assert_allclose(res["w1"], want, rtol=1e-6)
    c0.close()
    c1.close(shutdown_servers=False)
    t.join(timeout=10)


def test_sparse_multi_partition_row_alignment():
    """Partitions land on row boundaries (align_bytes) and per-partition
    local ids are remapped correctly."""
    rows, width = 256, 32            # 32KB total
    port, t = _server(1)
    c = PSClient([f"127.0.0.1:{port}"], worker_id=0)
    ctx = _ctx("emb", rows, width, 1, partition_bytes=8192)  # 4 partitions
    assert len(ctx.partitions) > 1
    for p in ctx.partitions:
        assert p.offset % (width * 4) == 0
        assert p.length % (width * 4) == 0
    rng = np.random.RandomState(1)
    g = _sparse_grad(rng, rows, width, 40)
    out = c.push_pull_rowsparse(ctx, g, average=False, num_workers=1)
    np.testing.assert_allclose(out, g, rtol=1e-6)
    # second round: different sparsity pattern (exercises re-zeroing)
    g2 = _sparse_grad(rng, rows, width, 3)
    out2 = c.push_pull_rowsparse(ctx, g2, average=False, num_workers=1)
    np.testing.assert_allclose(out2, g2, rtol=1e-6)
    c.close()
    t.join(timeout=10)


def test_sparse_and_dense_pushes_mix_in_one_round():
    """A round may mix sparse and dense pushes: scatter-add composes with
    the dense first-copy/sum protocol."""
    rows, width = 32, 8
    port, t = _server(2)
    addr = [f"127.0.0.1:{port}"]
    c0, c1 = PSClient(addr, worker_id=0), PSClient(addr, worker_id=1)
    ctx0 = _ctx("mix", rows, width, 2)
    ctx1 = _ctx("mix", rows, width, 2)
    rng = np.random.RandomState(2)
    g_sparse = _sparse_grad(rng, rows, width, 5)
    g_dense = rng.randn(rows, width).astype(np.float32)
    res = {}

    def w_sparse():
        res["s"] = c0.push_pull_rowsparse(ctx0, g_sparse, average=False,
                                          num_workers=2)

    def w_dense():
        res["d"] = c1.push_pull(ctx1, g_dense.reshape(-1).copy(),
                                average=False, num_workers=2)

    th = threading.Thread(target=w_dense, daemon=True)
    th.start()
    w_sparse()
    th.join(timeout=30)
    assert not th.is_alive()
    want = g_sparse + g_dense
    np.testing.assert_allclose(res["s"], want, rtol=1e-6)
    np.testing.assert_allclose(res["d"].reshape(rows, width), want,
                               rtol=1e-6)
    c0.close()
    c1.close(shutdown_servers=False)
    t.join(timeout=10)


def test_sparse_bad_ids_rejected():
    """Out-of-range row ids error-reply without corrupting the store."""
    rows, width = 16, 8
    port, t = _server(1)
    c = PSClient([f"127.0.0.1:{port}"], worker_id=0)
    ctx = _ctx("bad", rows, width, 1)
    c.ensure_init(ctx, rows * width * 4)
    cmd = get_command_type(RequestType.ROW_SPARSE_PUSH_PULL,
                           DataType.FLOAT32)
    payload = b"".join((
        np.uint32(1).tobytes(), np.uint32(width).tobytes(),
        np.int32(rows + 5).tobytes(),            # out of range
        np.ones(width, np.float32).tobytes(),
    ))
    with pytest.raises(RuntimeError, match="push failed"):
        c.zpush(0, ctx.partitions[0].key, np.frombuffer(payload, np.uint8),
                cmd)
    # the store still works with a valid round
    g = _sparse_grad(np.random.RandomState(3), rows, width, 2)
    out = c.push_pull_rowsparse(ctx, g, average=False, num_workers=1)
    np.testing.assert_allclose(out, g, rtol=1e-6)
    c.close()
    t.join(timeout=10)


def test_rowsparse_public_api(monkeypatch):
    """bps.push_pull_rowsparse end-to-end through init()."""
    from byteps_tpu.core.state import GlobalState

    port = _PORT[0]
    _PORT[0] += 1
    monkeypatch.setenv("DMLC_NUM_WORKER", "1")
    monkeypatch.setenv("DMLC_NUM_SERVER", "1")
    monkeypatch.setenv("DMLC_PS_ROOT_URI", "127.0.0.1")
    monkeypatch.setenv("DMLC_PS_ROOT_PORT", str(port))
    monkeypatch.setenv("BYTEPS_FORCE_DISTRIBUTED", "1")
    server = threading.Thread(
        target=run_server,
        args=(port, Config(num_workers=1, num_servers=1)), daemon=True)
    server.start()
    GlobalState._instance = None
    import byteps_tpu as bps
    bps.init()
    try:
        g = _sparse_grad(np.random.RandomState(4), 128, 16, 10)
        out = np.asarray(bps.push_pull_rowsparse(g, "emb/table",
                                                 average=False))
        np.testing.assert_allclose(out, g, rtol=1e-6)
    finally:
        bps.shutdown()
        server.join(timeout=10)
        GlobalState._instance = None


def test_rowsparse_through_scheduler_multipartition(monkeypatch):
    """The public API rides the priority pipeline; multiple row-aligned
    partitions fan out as scheduled tasks with prebuilt sparse payloads."""
    from byteps_tpu.core.state import GlobalState

    port = _PORT[0]
    _PORT[0] += 1
    monkeypatch.setenv("DMLC_NUM_WORKER", "1")
    monkeypatch.setenv("DMLC_NUM_SERVER", "1")
    monkeypatch.setenv("DMLC_PS_ROOT_URI", "127.0.0.1")
    monkeypatch.setenv("DMLC_PS_ROOT_PORT", str(port))
    monkeypatch.setenv("BYTEPS_FORCE_DISTRIBUTED", "1")
    monkeypatch.setenv("BYTEPS_PARTITION_BYTES", "8192")
    server = threading.Thread(
        target=run_server,
        args=(port, Config(num_workers=1, num_servers=1)), daemon=True)
    server.start()
    GlobalState._instance = None
    import byteps_tpu as bps
    bps.init()
    try:
        from byteps_tpu.core.state import get_state
        assert get_state().scheduler is not None
        rows, width = 256, 32       # 32KB -> 4 partitions at 8KB
        g = _sparse_grad(np.random.RandomState(5), rows, width, 30)
        out = np.asarray(bps.push_pull_rowsparse(g, "emb/big",
                                                 average=False))
        np.testing.assert_allclose(out, g, rtol=1e-6)
        ctx = get_state().registry.init_tensor(
            "emb/big", rows * width * 4, None, align_bytes=width * 4)
        assert len(ctx.partitions) > 1
        # second round with a different pattern
        g2 = _sparse_grad(np.random.RandomState(6), rows, width, 4)
        out2 = np.asarray(bps.push_pull_rowsparse(g2, "emb/big",
                                                  average=False))
        np.testing.assert_allclose(out2, g2, rtol=1e-6)
    finally:
        bps.shutdown()
        server.join(timeout=10)
        GlobalState._instance = None


def test_ps_train_step_rowsparse_params(monkeypatch):
    """make_ps_train_step(rowsparse_params=("embed",)): the embedding
    gradient travels row-sparse and training still converges to the same
    trajectory as the dense path (1 worker => both are exact)."""
    import jax
    import jax.numpy as jnp
    import optax

    from byteps_tpu.core.state import GlobalState
    from byteps_tpu.jax.train import make_ps_train_step
    from byteps_tpu.models import llama

    port = _PORT[0]
    _PORT[0] += 1
    monkeypatch.setenv("DMLC_NUM_WORKER", "1")
    monkeypatch.setenv("DMLC_NUM_SERVER", "1")
    monkeypatch.setenv("DMLC_PS_ROOT_URI", "127.0.0.1")
    monkeypatch.setenv("DMLC_PS_ROOT_PORT", str(port))
    monkeypatch.setenv("BYTEPS_FORCE_DISTRIBUTED", "1")
    server = threading.Thread(
        target=run_server,
        args=(port, Config(num_workers=1, num_servers=1)), daemon=True)
    server.start()
    GlobalState._instance = None
    import byteps_tpu as bps
    bps.init()
    try:
        from byteps_tpu.core.state import get_state
        import dataclasses
        cfg = dataclasses.replace(llama.LlamaConfig.tiny(vocab_size=64),
                                  dtype=jnp.float32)
        tx = optax.sgd(0.1)

        def run(**kw):
            params = llama.init_params(jax.random.PRNGKey(0), cfg)
            opt = tx.init(params)
            step = make_ps_train_step(
                lambda p, b: llama.loss_fn(p, b, cfg), tx,
                get_state().mesh, **kw)
            toks = jnp.asarray(np.arange(8 * 33).reshape(8, 33) % 13,
                               jnp.int32)
            for _ in range(3):
                params, opt, loss = step(params, opt, {"tokens": toks})
            return params, float(loss)

        p_dense, l_dense = run()
        p_sparse, l_sparse = run(rowsparse_params=("embed", "lm_head"))
        assert np.isclose(l_dense, l_sparse, rtol=1e-5)
        for a, b in zip(jax.tree.leaves(p_dense), jax.tree.leaves(p_sparse)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-5, atol=1e-6)
    finally:
        bps.shutdown()
        server.join(timeout=10)
        GlobalState._instance = None
