"""Full-stack PS integration: bps.init() with DMLC_NUM_SERVER>0 and
BYTEPS_FORCE_DISTRIBUTED connects the native PS client, eager push_pull
round-trips through the server, and make_ps_train_step trains — the
reference's canonical single-worker-full-path test env
(tests/meta_test.py:27-58)."""

import os
import threading

import numpy as np
import optax
import pytest

from byteps_tpu.config import Config
from byteps_tpu.server import run_server

_PORT = [19800]


@pytest.fixture()
def ps_env(monkeypatch):
    """One worker + one server on loopback, force-distributed."""
    from byteps_tpu.core.state import GlobalState

    port = _PORT[0]
    _PORT[0] += 1
    monkeypatch.setenv("DMLC_NUM_WORKER", "1")
    monkeypatch.setenv("DMLC_NUM_SERVER", "1")
    monkeypatch.setenv("DMLC_PS_ROOT_URI", "127.0.0.1")
    monkeypatch.setenv("DMLC_PS_ROOT_PORT", str(port))
    monkeypatch.setenv("BYTEPS_FORCE_DISTRIBUTED", "1")
    server = threading.Thread(
        target=run_server,
        args=(port, Config(num_workers=1, num_servers=1)), daemon=True)
    server.start()

    GlobalState._instance = None
    import byteps_tpu as bps
    bps.init()
    yield bps
    bps.shutdown()
    server.join(timeout=10)
    GlobalState._instance = None


def test_init_connects_ps(ps_env):
    from byteps_tpu.core.state import get_state
    assert get_state().ps_client is not None
    assert ps_env.size() == 1


def test_eager_push_pull_via_ps(ps_env):
    x = np.random.RandomState(0).randn(8, 100).astype(np.float32)
    out = ps_env.push_pull(x, name="g0", average=True, stacked=True)
    np.testing.assert_allclose(np.asarray(out), x.mean(0), rtol=1e-5,
                               atol=1e-6)
    # partitioned: force multiple keys via a large tensor
    big = np.random.RandomState(1).randn(8, 300_000).astype(np.float32)
    out2 = ps_env.push_pull(big, name="g_big", average=False, stacked=True)
    np.testing.assert_allclose(np.asarray(out2), big.sum(0), rtol=1e-4,
                               atol=1e-4)


def test_ps_train_step(ps_env):
    import jax
    from byteps_tpu.core.state import get_state
    from byteps_tpu.jax.train import make_ps_train_step
    from byteps_tpu.models import mlp

    mesh = get_state().mesh
    cfg = mlp.MLPConfig(in_dim=32, hidden=(16,), n_classes=4)
    params = mlp.init_params(jax.random.PRNGKey(0), cfg)
    tx = optax.sgd(0.1)
    step = make_ps_train_step(lambda p, b: mlp.loss_fn(p, b, cfg), tx, mesh)
    opt = tx.init(params)
    rng = np.random.RandomState(0)
    x = rng.randn(128, 32).astype(np.float32)
    y = np.argmax(x @ rng.randn(32, 4), -1).astype(np.int32)
    losses = []
    for _ in range(15):
        params, opt, loss = step(params, opt, {"x": x, "y": y})
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.8, (losses[0], losses[-1])


def test_suspend_resume_with_ps(ps_env):
    """Elastic: suspend drops the PS connection (servers stay up), resume
    reconnects and keys still work."""
    from byteps_tpu.core.state import get_state
    x = np.ones((8, 50), np.float32)
    ps_env.push_pull(x, name="el0", stacked=True)
    ps_env.suspend()
    assert get_state().ps_client is None
    ps_env.resume(num_workers=1, num_servers=1)
    assert get_state().ps_client is not None
    out = ps_env.push_pull(x * 2, name="el0", average=False, stacked=True)
    np.testing.assert_allclose(np.asarray(out), 16.0)
