"""Pipeline scheduler tests: priority ordering, credit admission,
completion counting, and the async handle API end-to-end against the
native PS (reference behaviors: scheduled_queue.cc, handle_manager)."""

import threading
import time

import numpy as np
import pytest

from byteps_tpu.config import Config
from byteps_tpu.core.registry import TensorRegistry
from byteps_tpu.core.scheduler import (
    Handle, HandleManager, PartitionTask, ScheduledQueue, TaskGroup,
)
from byteps_tpu.core.types import DataType, Partition, TensorContext
from byteps_tpu.server import run_server
from byteps_tpu.server.client import PSClient

_PORT = [20100]


def mk_task(key, priority, nbytes=100):
    ctx = TensorContext(name=f"t{key}", declared_key=key,
                        dtype=DataType.FLOAT32)
    part = Partition(key=key, index=0, offset=0, length=nbytes)
    group = TaskGroup(ctx, 1, lambda e: None)
    return PartitionTask(ctx, part, priority, 0, None, None, group, 0)


def test_queue_priority_order():
    q = ScheduledQueue()
    q.add_task(mk_task(key=3, priority=-3))
    q.add_task(mk_task(key=1, priority=-1))
    q.add_task(mk_task(key=2, priority=-2))
    # (priority desc, key asc) -> -1 first (scheduled_queue.cc:82-102)
    assert q.get_task().key == 1
    assert q.get_task().key == 2
    assert q.get_task().key == 3


def test_queue_key_tiebreak():
    q = ScheduledQueue()
    q.add_task(mk_task(key=9, priority=0))
    q.add_task(mk_task(key=4, priority=0))
    assert q.get_task().key == 4
    assert q.get_task().key == 9


def test_queue_credit_blocks_admission():
    q = ScheduledQueue(credit_bytes=150)
    q.add_task(mk_task(key=0, priority=0, nbytes=100))
    q.add_task(mk_task(key=1, priority=0, nbytes=100))
    t0 = q.get_task()
    assert t0.key == 0
    got = []

    def getter():
        got.append(q.get_task())

    th = threading.Thread(target=getter)
    th.start()
    time.sleep(0.3)
    assert got == []               # only 50 bytes credit left: blocked
    q.report_finish(t0)            # returns credit
    th.join(timeout=5)
    assert got and got[0].key == 1


def test_queue_serializes_same_key():
    """Two tasks for the same key never run concurrently: the second is
    held until report_finish of the first, so overlapping push_pulls of one
    tensor can't interleave server aggregation rounds."""
    q = ScheduledQueue()
    first, second = mk_task(key=7, priority=0), mk_task(key=7, priority=0)
    other = mk_task(key=8, priority=-1)  # lower priority, different key
    q.add_task(first)
    q.add_task(second)
    q.add_task(other)
    t0 = q.get_task()
    assert t0 is first
    # key 7 in flight: next admission skips `second` and takes key 8
    t1 = q.get_task()
    assert t1 is other
    got = []
    th = threading.Thread(target=lambda: got.append(q.get_task()))
    th.start()
    time.sleep(0.2)
    assert got == []               # second still blocked on in-flight key
    q.report_finish(t0)
    th.join(timeout=5)
    assert got and got[0] is second


def test_add_task_after_stop_raises():
    q = ScheduledQueue()
    q.stop()
    with pytest.raises(RuntimeError):
        q.add_task(mk_task(key=0, priority=0))


def test_stop_fails_queued_tasks():
    """Tasks still queued at stop() resolve their groups with an error so
    synchronize() raises instead of hanging."""
    errs = []
    ctx = TensorContext(name="t", declared_key=0, dtype=DataType.FLOAT32)
    g = TaskGroup(ctx, 1, lambda e: errs.append(e))
    part = Partition(key=0, index=0, offset=0, length=10)
    q = ScheduledQueue()
    q.add_task(PartitionTask(ctx, part, 0, 0, None, None, g, 0))
    q.stop()
    assert len(errs) == 1 and isinstance(errs[0], RuntimeError)


def test_task_group_counts_partitions():
    fired = []
    ctx = TensorContext(name="t", declared_key=0, dtype=DataType.FLOAT32)
    g = TaskGroup(ctx, 3, lambda e: fired.append(e))
    g.partition_done()
    g.partition_done()
    assert fired == []
    g.partition_done()
    assert fired == [None]


def test_task_group_propagates_error():
    fired = []
    ctx = TensorContext(name="t", declared_key=0, dtype=DataType.FLOAT32)
    g = TaskGroup(ctx, 2, lambda e: fired.append(e))
    g.partition_done(RuntimeError("boom"))
    g.partition_done()
    assert isinstance(fired[0], RuntimeError)


def test_handle_manager():
    hm = HandleManager()
    h = hm.allocate("x")
    assert not hm.poll(h.id)
    h._finish(np.ones(3), None)
    assert hm.poll(h.id)
    np.testing.assert_array_equal(hm.wait_and_clear(h.id), np.ones(3))
    with pytest.raises(KeyError):
        hm.get(h.id)


def test_async_api_end_to_end(monkeypatch):
    """push_pull_async/poll/synchronize through the live pipeline against
    a real loopback server."""
    from byteps_tpu.core.state import GlobalState

    port = _PORT[0]
    _PORT[0] += 1
    monkeypatch.setenv("DMLC_NUM_WORKER", "1")
    monkeypatch.setenv("DMLC_NUM_SERVER", "1")
    monkeypatch.setenv("DMLC_PS_ROOT_URI", "127.0.0.1")
    monkeypatch.setenv("DMLC_PS_ROOT_PORT", str(port))
    server = threading.Thread(
        target=run_server, args=(port, Config(num_workers=1, num_servers=1)),
        daemon=True)
    server.start()

    GlobalState._instance = None
    import byteps_tpu as bps
    bps.init()
    try:
        rng = np.random.RandomState(0)
        tensors = {f"g{i}": rng.randn(5000).astype(np.float32)
                   for i in range(6)}
        handles = {n: bps.push_pull_async(x, n) for n, x in tensors.items()}
        for n, hd in handles.items():
            out = bps.synchronize(hd, timeout=30)
            np.testing.assert_allclose(out, tensors[n], rtol=1e-6)
        # poll on a fresh handle eventually turns true
        hd = bps.push_pull_async(tensors["g0"], "g0")
        deadline = time.time() + 30
        while not bps.poll(hd):
            assert time.time() < deadline
            time.sleep(0.01)
        bps.synchronize(hd)
    finally:
        bps.shutdown()
        server.join(timeout=10)
        GlobalState._instance = None


def test_queue_priority_with_compressed_tasks():
    """Compressed partitions obey the same (priority desc, key asc)
    admission order as dense ones — compression rides the scheduled queue,
    it doesn't bypass it (operations.cc:199-204)."""
    from byteps_tpu.ops.compression.host import make_host_codec

    q = ScheduledQueue()
    stack = make_host_codec({"compressor": "onebit"}, 64)

    def mk(key, priority, stack=None):
        t = mk_task(key, priority)
        t.stack = stack
        return t

    q.add_task(mk(3, -3, stack))          # compressed, least urgent
    q.add_task(mk(1, -1))                 # dense, most urgent
    q.add_task(mk(2, -2, stack))          # compressed, middle
    got = [q.get_task() for _ in range(3)]
    assert [t.key for t in got] == [1, 2, 3]
    assert got[1].stack is stack and got[0].stack is None


def test_handle_manager_error_and_cleared_semantics():
    """Round-4 review regressions: an errored handle is removed by
    wait_and_clear (a leaked entry pins gradient-sized buffers via the
    error traceback); poll on a cleared id reports done (the reference
    PollHandle contract) instead of raising."""
    hm = HandleManager()
    h = hm.allocate("bad")
    h._finish(None, RuntimeError("boom"))
    with pytest.raises(RuntimeError, match="boom"):
        hm.wait_and_clear(h.id)
    # the errored handle is gone, not leaked
    with pytest.raises(KeyError, match="already-synchronized"):
        hm.get(h.id)
    # poll on the cleared id reports done rather than crashing
    assert hm.poll(h.id) is True
    # a pending handle that times out is KEPT for retry
    h2 = hm.allocate("slow")
    with pytest.raises(TimeoutError):
        hm.wait_and_clear(h2.id, timeout=0.01)
    assert not hm.poll(h2.id)
    h2._finish(np.zeros(1), None)
    hm.wait_and_clear(h2.id)


def test_poll_rejects_never_allocated_ids():
    """poll's done-when-cleared contract covers ids actually handed out;
    a stale/garbage id from a caller bug raises instead of masquerading
    as completion (round-5 advisor finding)."""
    hm = HandleManager()
    h = hm.allocate("x")
    with pytest.raises(KeyError, match="never allocated"):
        hm.poll(h.id + 1)
    with pytest.raises(KeyError, match="never allocated"):
        hm.poll(-1)
    h._finish(np.zeros(1), None)
    hm.wait_and_clear(h.id)
    assert hm.poll(h.id) is True  # cleared (real) id still reports done


def test_discard_abandons_handle():
    """Abandon-on-timeout callers (metric callbacks) drop sibling handles
    via discard so result buffers don't pin memory for the process life;
    discard on an already-cleared id is a no-op."""
    hm = HandleManager()
    h = hm.allocate("x")
    hm.discard(h.id)
    with pytest.raises(KeyError):
        hm.get(h.id)
    assert hm.poll(h.id) is True  # discarded == cleared for pollers
    hm.discard(h.id)  # idempotent


def test_per_key_priority_is_pinned(monkeypatch):
    """Two rounds of one tensor submitted with different explicit
    priorities must NOT reorder in the queue: the server counts pushes
    positionally per worker per key, so admitting round N+1 before
    round N would silently swap aggregation rounds. The first
    submission's priority is pinned per key (round-4 review fix)."""
    from byteps_tpu.core.state import GlobalState

    port = _PORT[0]
    _PORT[0] += 1
    monkeypatch.setenv("DMLC_NUM_WORKER", "1")
    monkeypatch.setenv("DMLC_NUM_SERVER", "1")
    monkeypatch.setenv("DMLC_PS_ROOT_URI", "127.0.0.1")
    monkeypatch.setenv("DMLC_PS_ROOT_PORT", str(port))
    server = threading.Thread(
        target=run_server, args=(port, Config(num_workers=1, num_servers=1)),
        daemon=True)
    server.start()

    GlobalState._instance = None
    import byteps_tpu as bps
    bps.init()
    try:
        from byteps_tpu.core.state import get_state

        sched = get_state().scheduler
        x = np.ones(256, np.float32)
        h1 = bps.push_pull_async(x, "pinp", average=False, priority=5)
        bps.synchronize(h1, timeout=30)
        # a different per-round priority is ignored (pinned at 5)
        h2 = bps.push_pull_async(x, "pinp", average=False, priority=9)
        bps.synchronize(h2, timeout=30)
        ctx = get_state().registry.get("pinp")
        assert sched._key_priority[ctx.declared_key] == 5
    finally:
        bps.shutdown()
        server.join(timeout=10)
        GlobalState._instance = None


def _mk_ctx(key: int, name: str = None) -> TensorContext:
    return TensorContext(name=name or f"t{key}", declared_key=key,
                         dtype=DataType.FLOAT32)


def test_pin_priority_first_submission_pins(monkeypatch):
    """_pin_priority unit contract (guards the production-order
    priority source against the cross-round reorder bug the pin exists
    for): the first submission's explicit priority pins; a differing
    per-call value warns EXACTLY once then is silently ignored; None
    follows the pin without warning."""
    from byteps_tpu.core import scheduler as sched_mod
    from byteps_tpu.core.scheduler import PipelineScheduler

    warned = []
    monkeypatch.setattr(
        sched_mod.log, "warning",
        lambda msg, *a, **k: warned.append(msg % tuple(a) if a else msg))
    sched = PipelineScheduler(None)
    try:
        ctx = _mk_ctx(7)
        assert sched._pin_priority(ctx, 5) == 5          # pins
        assert sched._pin_priority(ctx, 9) == 5          # ignored + warns
        assert len(warned) == 1 and "pinned" in warned[0]
        assert sched._pin_priority(ctx, 3) == 5          # silent now
        assert sched._pin_priority(ctx, 9) == 5          # still silent
        assert len(warned) == 1, warned
        # None = "no opinion": follows the pin silently (a fallback-path
        # submission of a production-pinned key must not trip the
        # mismatch warning)
        assert sched._pin_priority(ctx, None) == 5
        assert len(warned) == 1, warned
        # an untouched key seeds the layer-order default from None
        assert sched._pin_priority(_mk_ctx(11), None) == -11
    finally:
        sched.stop()


def test_pinned_priority_preserves_round_order():
    """Two queued rounds of one tensor carrying DIFFERENT requested
    priorities are admitted in round order once both resolve through
    the pin — the exact cross-round reorder the pin guards against
    (the server counts pushes positionally per worker per key)."""
    from byteps_tpu.core.scheduler import PipelineScheduler

    sched = PipelineScheduler(None)
    try:
        ctx = _mk_ctx(4)
        p1 = sched._pin_priority(ctx, 5)
        p2 = sched._pin_priority(ctx, 9)  # would overtake if honored
        assert (p1, p2) == (5, 5)
        q = ScheduledQueue()
        t1, t2 = mk_task(key=4, priority=p1), mk_task(key=4, priority=p2)
        q.add_task(t1)
        q.add_task(t2)
        got = q.get_task()
        assert got is t1, "round N+1 admitted before round N"
        q.report_finish(got)
        assert q.get_task() is t2
    finally:
        sched.stop()


def test_production_priority_orders_by_first_export():
    """production_priority (the streamed-export priority source):
    ordinals follow FIRST-EXPORT order, not declared-key order; repeat
    calls are stable; the assignment pins, so later default submissions
    agree; admission order follows production order."""
    from byteps_tpu.core.scheduler import PipelineScheduler

    sched = PipelineScheduler(None)
    try:
        c9, c3, c5 = _mk_ctx(9), _mk_ctx(3), _mk_ctx(5)
        assert sched.production_priority(c9) == 0   # produced first
        assert sched.production_priority(c3) == -1
        assert sched.production_priority(c5) == -2
        assert sched.production_priority(c9) == 0   # stable
        assert sched.export_order() == {9: 0, 3: 1, 5: 2}
        # the assignment pinned: a later None submission follows it
        assert sched._pin_priority(c9, None) == 0
        # admission order = production order (not key order): key 9,
        # first exported, wins although its declared key is largest
        q = ScheduledQueue()
        q.add_task(mk_task(key=3, priority=sched.production_priority(c3)))
        q.add_task(mk_task(key=5, priority=sched.production_priority(c5)))
        q.add_task(mk_task(key=9, priority=sched.production_priority(c9)))
        assert [q.get_task().key for _ in range(3)] == [9, 3, 5]
    finally:
        sched.stop()
