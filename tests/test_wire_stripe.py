"""Striped cross-host wire plane (PR 17): connection striping, batched
submission rings, decompress-on-the-fabric.

The BYTEPS_WIRE_STRIPES / BYTEPS_WIRE_RING / BYTEPS_STRIPE_CHUNK_BYTES
knobs are latched per process in the native lib, so the parity matrix
runs each arm in a fresh subprocess over REAL loopback TCP
(BYTEPS_ENABLE_IPC=0 — the shm descriptor tier would bypass the wire
entirely) and compares result hashes across arms:

- bitwise parity stripes on/off across dense fused-PUSHPULL (striped),
  two-worker fused aggregation, bf16, rowsparse and lossless traffic;
- out-of-order reassembly: a 8 KB stripe chunk splits each 1 MB
  payload into ~128 segments interleaved over 4 TCP conns, so segment
  arrival order at the server is scheduler-dependent — the per-(sender,
  key) seq gate must still deliver rounds in order;
- short-write recovery: BYTEPS_SOCK_BUF_BYTES=64 KB (the clamp floor)
  forces partial sendmsg() completions on every multi-segment batch;
- replay-epoch dedup: a retried fused round (same round, bumped
  attempt) is answered from the aggregate, never re-folded;
- single-stripe death: killing one data conn degrades stripe width,
  not the request — the group only dies when all striped conns die;
- fused decode A/B: BYTEPS_FUSED_DECODE on/off is bitwise identical
  for the lossless tier (decode-into-accumulator vs decode-then-fold),
  proven by the `fused_decode_folds` stage counter.
"""

import hashlib
import json
import os
import subprocess
import sys
import threading

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# One full traffic battery in a child process; prints result hashes +
# wire counters as JSON so the parent can diff arms bitwise without
# shipping arrays across the pipe.
_BATTERY = r"""
import hashlib, json, os, sys, threading
sys.path.insert(0, os.environ["BPS_REPO"])
import numpy as np
from byteps_tpu.config import Config
from byteps_tpu.core.registry import TensorRegistry
from byteps_tpu.core.types import DataType, RequestType, get_command_type
from byteps_tpu.server import run_server, stage_stats
from byteps_tpu.server.client import PSClient
from byteps_tpu.server.compressed import CompressedTensor
from byteps_tpu.utils.net import free_port, wait_port

port = free_port()
cfg = Config(num_workers=2, num_servers=1)
server = threading.Thread(target=run_server, args=(port, cfg), daemon=True)
server.start()
wait_port(port)
addr = [f"127.0.0.1:{port}"]
c0 = PSClient(addr, worker_id=0)
c1 = PSClient(addr, worker_id=1)
CMD = get_command_type(RequestType.DEFAULT_PUSH_PULL, DataType.FLOAT32)
res = {}

def fused(c, key, x, out, epoch):
    done = threading.Event(); err = [None]
    def cb(n, e):
        err[0] = e; done.set()
    c.zpushpull_async(0, key, x, out, CMD, cb, epoch=epoch)
    assert done.wait(120), "fused pushpull timed out"
    if err[0]:
        raise err[0]

rng = np.random.RandomState(11)
n = 262144  # 1 MB payload: ~128 segments at the 8 KB test chunk
x0 = rng.randn(n).astype(np.float32)

def init_both(key, zero, cmd):
    # the init push is the per-key init barrier: both workers must be
    # in it at once or the first blocks forever
    t = threading.Thread(target=c1.init_key, args=(0, key, zero, cmd))
    t.start()
    c0.init_key(0, key, zero, cmd)
    t.join(timeout=60)
    assert not t.is_alive(), "init barrier wedged"

# --- dense fused PUSHPULL, 3 rounds (2 workers; both must fold for
# ALL_RECV, f32 a+b is commutative so the sum is order-independent) ---
z = np.zeros_like(x0)
init_both(5, z, CMD)
acc = hashlib.sha256()
for r in range(1, 4):
    xa = (x0 * r).astype(np.float32)
    xb = (x0 + r).astype(np.float32)
    oa, ob = np.empty_like(xa), np.empty_like(xb)
    tb = threading.Thread(target=fused, args=(c1, 5, xb, ob, r << 16))
    tb.start()
    fused(c0, 5, xa, oa, r << 16)
    tb.join(timeout=120)
    want = xa + xb
    assert np.array_equal(oa, want), f"dense round {r} w0 parity"
    assert np.array_equal(ob, want), f"dense round {r} w1 parity"
    acc.update(oa.tobytes())
res["dense"] = acc.hexdigest()

# --- replay-epoch dedup across stripes: retry of round 4 (attempt 1)
# must answer from the aggregate, never double-fold ---
xa = (x0 * 4).astype(np.float32)
xb = (x0 + 4).astype(np.float32)
oa, ob = np.empty_like(xa), np.empty_like(xb)
tb = threading.Thread(target=fused, args=(c1, 5, xb, ob, 4 << 16))
tb.start()
fused(c0, 5, xa, oa, 4 << 16)
tb.join(timeout=120)
o2 = np.empty_like(xa)
fused(c0, 5, xa, o2, (4 << 16) | 1)  # replayed attempt
want = xa + xb
assert np.array_equal(oa, want) and np.array_equal(o2, want), \
    "replayed striped round double-counted"
res["replay"] = hashlib.sha256(o2.tobytes()).hexdigest()

# --- bf16 two-op (regression guard: the multi-conn group must not
# disturb non-striped traffic) ---
import ml_dtypes
CMD_BF = get_command_type(RequestType.DEFAULT_PUSH_PULL,
                          DataType.BFLOAT16)
xh = (rng.randn(65536) * 100).astype(ml_dtypes.bfloat16)
zb = np.zeros_like(xh)
init_both(6, zb, CMD_BF)
c0.zpush(0, 6, xh, CMD_BF)
c1.zpush(0, 6, xh, CMD_BF)
ob = np.empty_like(xh)
c0.zpull(0, 6, ob, CMD_BF)
want_bf = (xh.astype(np.float32) * 2).astype(ml_dtypes.bfloat16)
assert ob.tobytes() == want_bf.tobytes(), "bf16 parity"
res["bf16"] = hashlib.sha256(ob.tobytes()).hexdigest()

# --- rowsparse (two-op wire) ---
reg = TensorRegistry(cfg)
W, R = 64, 512
ctx = reg.init_tensor("emb", R * W * 4, DataType.FLOAT32,
                      align_bytes=W * 4)
g = np.zeros((R, W), np.float32)
idx = rng.choice(R, 40, replace=False)
g[idx] = rng.randn(40, W)

def rs(c, out):
    out.append(c.push_pull_rowsparse(ctx, g, average=False))

r1 = []
tb = threading.Thread(target=rs, args=(c1, r1))
tb.start()
o_rs = c0.push_pull_rowsparse(ctx, g, average=False)
tb.join(timeout=120)
assert np.array_equal(o_rs, g * 2), "rowsparse parity"
res["rowsparse"] = hashlib.sha256(np.ascontiguousarray(o_rs)
                                  .tobytes()).hexdigest()

# --- lossless codec (DoPushCompressed: fused decode-into-fold when
# BYTEPS_FUSED_DECODE=1, the default) ---
nl = 131072
ctx_l = reg.init_tensor("lz", nl * 4, DataType.FLOAT32)
ct0 = CompressedTensor(c0, ctx_l, {"compressor": "lossless"}, 2)
ct1 = CompressedTensor(c1, ctx_l, {"compressor": "lossless"}, 2)
xl = rng.randn(nl).astype(np.float32)
xl[:4] = [np.float32("nan"), np.float32("inf"), -0.0, 1e-42]
r2 = []
tb = threading.Thread(
    target=lambda: r2.append(ct1.push_pull(xl, average=False)))
tb.start()
o_l = ct0.push_pull(xl, average=False)
tb.join(timeout=120)
want_l = xl + xl
assert np.asarray(o_l).tobytes() == want_l.tobytes(), "lossless parity"
res["lossless"] = hashlib.sha256(np.asarray(o_l).tobytes()).hexdigest()

# --- wire counters: the proof surface the parent asserts on ---
st = stage_stats()
res["stats"] = {k: int(st[k]) for k in (
    "stripe_segs", "stripe_bytes", "tx_batches", "tx_msgs",
    "rx_batches", "rx_msgs", "fused_decode_folds", "reg_blocks",
    "reg_miss")}
res["transport"] = c0.transport_stats()
res["transport1"] = c1.transport_stats()
res["conn_bytes"] = c0.stripe_conn_bytes(0)
res["conn_bytes1"] = c1.stripe_conn_bytes(0)
c0.close()
c1.close()
server.join(timeout=20)
print("BATTERY " + json.dumps(res))
"""

# Single-stripe death: kill one data conn between rounds; the striper
# must drop it from the live set and complete on the survivors.
_DEATH = r"""
import json, os, sys, threading, time
sys.path.insert(0, os.environ["BPS_REPO"])
import numpy as np
from byteps_tpu.config import Config
from byteps_tpu.core.types import DataType, RequestType, get_command_type
from byteps_tpu.server import run_server
from byteps_tpu.server.client import PSClient
from byteps_tpu.utils.net import free_port, wait_port

port = free_port()
cfg = Config(num_workers=1, num_servers=1)
server = threading.Thread(target=run_server, args=(port, cfg), daemon=True)
server.start()
wait_port(port)
c = PSClient([f"127.0.0.1:{port}"], worker_id=0)
CMD = get_command_type(RequestType.DEFAULT_PUSH_PULL, DataType.FLOAT32)

def fused(key, x, out, epoch):
    done = threading.Event(); err = [None]
    def cb(n, e):
        err[0] = e; done.set()
    c.zpushpull_async(0, key, x, out, CMD, cb, epoch=epoch)
    assert done.wait(120), "fused pushpull timed out"
    if err[0]:
        raise err[0]

rng = np.random.RandomState(3)
x = rng.randn(262144).astype(np.float32)
c.init_key(0, 9, np.zeros_like(x), CMD)
out = np.empty_like(x)
fused(9, x, out, 1 << 16)
assert np.array_equal(out, x), "pre-kill parity"
segs_before = c.transport_stats()["stripe_segs"]
assert segs_before > 0, "striper never engaged before the kill"

# kill a NON-control data conn (conn 0 is the control lane) and let
# the server's conn loop observe the close (StripeReset, gate resync)
assert c.kill_stripe(0, 2), "kill hook failed"
time.sleep(0.3)

for r in range(2, 5):
    xr = (x * r).astype(np.float32)
    fused(9, xr, out, r << 16)
    assert np.array_equal(out, xr), f"post-kill round {r} parity"
segs_after = c.transport_stats()["stripe_segs"]
assert segs_after > segs_before, "post-kill rounds stopped striping"
# control lane survived the data-conn death
assert c.server_stats(0) is not None, "control lane died with the stripe"
c.close()
server.join(timeout=20)
print("DEATH_OK " + json.dumps({"segs": segs_after}))
"""


def _run_child(script, extra_env, timeout=300):
    env = {
        **os.environ,
        "BPS_REPO": REPO,
        "JAX_PLATFORMS": "cpu",
        "BYTEPS_ENABLE_IPC": "0",  # real TCP or the wire is bypassed
        **extra_env,
    }
    proc = subprocess.run([sys.executable, "-c", script], env=env,
                          capture_output=True, text=True, timeout=timeout)
    out = proc.stdout + proc.stderr
    assert proc.returncode == 0, out[-4000:]
    return out


def _battery(extra_env):
    out = _run_child(_BATTERY, extra_env)
    line = [ln for ln in out.splitlines() if ln.startswith("BATTERY ")]
    assert line, out[-4000:]
    return json.loads(line[-1][len("BATTERY "):])


_STRIPED_ENV = {
    "BYTEPS_WIRE_STRIPES": "4",
    "BYTEPS_STRIPE_CHUNK_BYTES": "8192",   # ~128 segs per 1MB payload
    "BYTEPS_SOCK_BUF_BYTES": "65536",      # clamp floor: short writes
}

_LEGS = ("dense", "replay", "bf16", "rowsparse", "lossless")


def test_stripe_parity_matrix():
    """Bitwise parity stripes on vs off across the traffic matrix, with
    out-of-order reassembly (8 KB chunks over 4 conns) and short-write
    recovery (64 KB socket buffers) riding the striped arm — plus the
    counter proofs that the striped arm actually striped and the
    control arm actually didn't."""
    striped = _battery(_STRIPED_ENV)
    plain = _battery({"BYTEPS_WIRE_STRIPES": "1"})

    for leg in _LEGS:
        assert striped[leg] == plain[leg], \
            f"stripes on/off disagree bitwise on the {leg} leg"

    # striped arm: the wire actually striped, and conservation holds —
    # client payload bytes + 72 B/segment framing == per-conn TX sums,
    # and the server reassembled every segment the clients sent
    for w in ("transport", "transport1"):
        t = striped[w]
        assert t["stripe_segs"] > 0, f"{w}: striper never engaged"
        conn = striped["conn_bytes" if w == "transport" else
                       "conn_bytes1"]
        assert conn[0] == 0, "control lane carried stripe traffic"
        assert sum(conn) == t["stripe_bytes"] + 72 * t["stripe_segs"], \
            "per-conn TX ledger violates byte conservation"
    sent_segs = (striped["transport"]["stripe_segs"]
                 + striped["transport1"]["stripe_segs"])
    sent_bytes = (striped["transport"]["stripe_bytes"]
                  + striped["transport1"]["stripe_bytes"])
    assert striped["stats"]["stripe_segs"] == sent_segs
    assert striped["stats"]["stripe_bytes"] == sent_bytes
    # ring + fused-decode instruments live on the striped arm
    s = striped["stats"]
    assert s["tx_batches"] > 0 and s["tx_msgs"] >= s["tx_batches"]
    assert s["rx_batches"] > 0 and s["rx_msgs"] > 0
    assert s["fused_decode_folds"] > 0, \
        "lossless folds never took the fused decode path"
    assert s["reg_blocks"] > 0, "no transport-registered blocks"

    # control arm: a 1-stripe group must never emit segments
    assert plain["transport"]["stripe_segs"] == 0
    assert plain["transport1"]["stripe_segs"] == 0
    assert plain["stats"]["stripe_segs"] == 0


def test_single_stripe_death_fails_over():
    """Killing one data conn mid-run degrades stripe width, never the
    request: later rounds still stripe over the survivors bitwise, and
    the control lane stays answerable."""
    out = _run_child(_DEATH, _STRIPED_ENV, timeout=240)
    assert "DEATH_OK" in out, out[-4000:]


def test_wire_ring_off_parity():
    """BYTEPS_WIRE_RING=0 (per-message blocking send/recv, the legacy
    wire) is bitwise identical to the batched default — the A/B lever
    bench --phase stripe_ab leans on."""
    ringless = _battery({**_STRIPED_ENV, "BYTEPS_WIRE_RING": "0"})
    striped = _battery(_STRIPED_ENV)
    for leg in _LEGS:
        assert ringless[leg] == striped[leg], \
            f"wire ring on/off disagree bitwise on the {leg} leg"
    # the ring-off arm must not count ring batches on the rx side
    assert ringless["stats"]["rx_batches"] == 0
    assert striped["stats"]["rx_batches"] > 0


def _nasty_f32(n, seed):
    x = np.random.RandomState(seed).randn(n).astype(np.float32)
    x[:6] = [np.float32("nan"), np.float32("inf"),
             np.float32("-inf"), -0.0, 1e-42, -1e-42]
    return x


def test_fused_decode_bitwise_ab():
    """Decompress-on-the-fabric A/B (in-process: BYTEPS_FUSED_DECODE is
    read per server instance): decode-into-accumulator and
    decode-then-fold produce bitwise-identical lossless aggregates, and
    the stage counter proves which path ran."""
    import threading as th

    from byteps_tpu.config import Config
    from byteps_tpu.core.registry import TensorRegistry
    from byteps_tpu.core.types import DataType
    from byteps_tpu.server import run_server
    from byteps_tpu.server.client import PSClient
    from byteps_tpu.server.compressed import CompressedTensor
    from byteps_tpu.utils.net import free_port, wait_port

    n = 65536
    x = _nasty_f32(n, seed=5)
    outs, folds = {}, {}
    for flag in ("0", "1"):
        os.environ["BYTEPS_FUSED_DECODE"] = flag
        try:
            port = free_port()
            cfg = Config(num_workers=1, num_servers=1)
            t = th.Thread(target=run_server, args=(port, cfg),
                          daemon=True)
            t.start()
            wait_port(port)
            c = PSClient([f"127.0.0.1:{port}"], worker_id=0)
            reg = TensorRegistry(cfg)
            ctx = reg.init_tensor(f"ab{flag}", n * 4, DataType.FLOAT32)
            ct = CompressedTensor(c, ctx, {"compressor": "lossless"}, 1)
            for r in range(2):
                out = ct.push_pull(x * (r + 1), average=False)
            outs[flag] = np.asarray(out).tobytes()
            st = c.server_stats(0)
            folds[flag] = st["fused_decode_folds"] if st else None
            c.close()
            t.join(timeout=20)
        finally:
            os.environ.pop("BYTEPS_FUSED_DECODE", None)
    assert outs["0"] == outs["1"], \
        "fused decode changed lossless aggregate bits"
    assert folds["1"] and folds["1"] > 0, \
        "BYTEPS_FUSED_DECODE=1 never took the fused path"
    assert folds["0"] == 0, \
        "BYTEPS_FUSED_DECODE=0 still took the fused path"
