"""Mixed-precision utilities (misc/) tests: policy casts, dynamic loss
scaling (skip/backoff/growth), fp32 master weights, and composition with
distributed_optimizer on the CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import optax

from byteps_tpu.core.state import get_state
from byteps_tpu.jax import distributed_optimizer
from byteps_tpu.jax.train import make_train_step
from byteps_tpu.misc import (
    MixedPrecisionPolicy, cast_to_compute, cast_to_param,
    dynamic_loss_scaling, mixed_precision_optimizer,
)
from byteps_tpu.misc.mixed_precision import current_loss_scale


def test_policy_casts():
    p = {"w": jnp.ones((4, 4), jnp.float32), "i": jnp.ones((2,), jnp.int32)}
    c = cast_to_compute(p, MixedPrecisionPolicy.bf16())
    assert c["w"].dtype == jnp.bfloat16
    assert c["i"].dtype == jnp.int32  # non-float leaves untouched
    back = cast_to_param(c, MixedPrecisionPolicy.bf16())
    assert back["w"].dtype == jnp.float32


def test_loss_scaling_skips_nonfinite_and_backs_off():
    tx = dynamic_loss_scaling(optax.sgd(0.1), init_scale=1024.0,
                              growth_interval=3)
    params = {"w": jnp.ones((3,), jnp.float32)}
    state = tx.init(params)
    s0 = float(current_loss_scale(state))
    assert s0 == 1024.0

    # finite scaled grads: update = lr * grad / scale
    g = {"w": jnp.full((3,), 2.0 * s0)}
    u, state = tx.update(g, state, params)
    np.testing.assert_allclose(np.asarray(u["w"]), -0.2, rtol=1e-6)

    # non-finite grads: step skipped, scale halves
    g_bad = {"w": jnp.array([1.0, jnp.inf, 2.0])}
    u, state = tx.update(g_bad, state, params)
    np.testing.assert_array_equal(np.asarray(u["w"]), 0.0)
    assert float(current_loss_scale(state)) == 512.0


def test_loss_scaling_grows_after_streak():
    tx = dynamic_loss_scaling(optax.sgd(0.1), init_scale=8.0,
                              growth_interval=2)
    params = {"w": jnp.ones((2,), jnp.float32)}
    state = tx.init(params)
    g = {"w": jnp.ones((2,), jnp.float32)}
    _, state = tx.update(g, state, params)   # good step 1
    _, state = tx.update(g, state, params)   # good step 2 -> grow
    assert float(current_loss_scale(state)) == 16.0


def test_master_weights_accumulate_small_updates():
    """Updates too small for bf16 rounding must still accumulate in the
    fp32 masters — the whole point of the imagenet18 arrangement."""
    policy = MixedPrecisionPolicy.bf16()
    tx = mixed_precision_optimizer(optax.sgd(1.0), policy)
    params = cast_to_compute({"w": jnp.ones((4,), jnp.float32)}, policy)
    assert params["w"].dtype == jnp.bfloat16
    state = tx.init(params)
    # one bf16 ulp at 1.0 is ~0.0078; push 1e-3 steps 8 times: each one
    # alone would round to nothing in bf16, together they must move w
    g = {"w": jnp.full((4,), 1e-3, jnp.bfloat16)}
    for _ in range(8):
        u, state = tx.update(g, state, params)
        params = optax.apply_updates(params, u)
    assert params["w"].dtype == jnp.bfloat16
    master = state.master["w"]
    np.testing.assert_allclose(np.asarray(master), 1.0 - 8e-3, rtol=1e-4)
    # the bf16 param tracks the rounded master
    np.testing.assert_allclose(np.asarray(params["w"].astype(jnp.float32)),
                               np.asarray(master.astype(jnp.bfloat16)
                                          .astype(jnp.float32)))


def test_composes_with_distributed_optimizer(bps):
    """fp16 end-to-end: scaled loss, push_pull-averaged grads, master
    weights — loss decreases on a tiny regression problem."""
    mesh = get_state().mesh
    policy = MixedPrecisionPolicy.fp16()
    tx = distributed_optimizer(
        dynamic_loss_scaling(
            mixed_precision_optimizer(optax.sgd(0.05), policy),
            init_scale=256.0, growth_interval=50))

    rng = np.random.RandomState(0)
    Xh = rng.randn(32, 8).astype(np.float32)
    yh = (Xh @ rng.randn(8, 1)).astype(np.float32)

    params = cast_to_compute(
        {"w": jnp.zeros((8, 1), jnp.float32)}, policy)

    def loss_fn(p, batch):
        # per-example scale column: batch entries shard over dp, scalars
        # can't — mean() recovers the scalar scale after sharding
        scale = jnp.mean(batch["scale"])
        x = batch["x"].astype(policy.compute_dtype)
        pred = x @ p["w"]
        loss = jnp.mean((pred.astype(jnp.float32)
                         - batch["y"]) ** 2)
        return loss * scale  # caller-side scaling

    step = make_train_step(loss_fn, tx, mesh)
    opt_state = tx.init(params)
    losses = []
    for _ in range(20):
        scale = float(current_loss_scale(opt_state))
        params, opt_state, loss = step(
            params, opt_state,
            {"x": Xh, "y": yh,
             "scale": np.full((32,), scale, np.float32)})
        losses.append(float(loss) / scale)
    assert losses[-1] < losses[0] * 0.5, losses
