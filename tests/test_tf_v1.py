"""TF1 graph-mode adapter surface (byteps_tpu/tensorflow/v1.py): the
compute_gradients-override DistributedOptimizer and
BroadcastGlobalVariablesHook driving real Sessions — the reference's
legacy API (tensorflow/__init__.py:141-268). Runs in subprocesses (graph
mode is process-global state; the TF2 adapter tests must not inherit
it)."""

import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_PIN = ("from byteps_tpu.utils.jax_compat import force_cpu; "
        "force_cpu(8); ")


def _run(body: str, env_extra=None, timeout=420):
    env = {**os.environ,
           "PYTHONPATH": REPO + os.pathsep
           + os.environ.get("PYTHONPATH", ""),
           **(env_extra or {})}
    return subprocess.run([sys.executable, "-c", _PIN + body], cwd=REPO,
                          capture_output=True, text=True, timeout=timeout,
                          env=env)


_TRAIN_V1 = r"""
import numpy as np
import tensorflow as tf
import byteps_tpu.tensorflow as bps
from byteps_tpu.tensorflow import v1 as bps_v1

bps.init()
g = tf.Graph()
with g.as_default():
    rng = np.random.RandomState(0)
    X = rng.randn(128, 8).astype(np.float32)
    Y = (X @ np.arange(8, dtype=np.float32)[:, None] * 0.1 + 0.5)
    x = tf.compat.v1.placeholder(tf.float32, [None, 8])
    y = tf.compat.v1.placeholder(tf.float32, [None, 1])
    w = tf.compat.v1.get_variable("w", [8, 1], tf.float32,
                                  tf.compat.v1.zeros_initializer())
    b = tf.compat.v1.get_variable("b", [1], tf.float32,
                                  tf.compat.v1.constant_initializer(7.0))
    loss = tf.reduce_mean(tf.square(x @ w + b - y))
    opt = bps_v1.DistributedOptimizer(
        tf.compat.v1.train.GradientDescentOptimizer(0.1))
    train_op = opt.minimize(loss)
    bcast = bps_v1.broadcast_global_variables(0)
    with tf.compat.v1.Session() as sess:
        sess.run(tf.compat.v1.global_variables_initializer())
        sess.run(bcast)
        l0 = sess.run(loss, {x: X, y: Y})
        for _ in range(40):
            sess.run(train_op, {x: X, y: Y})
        l1 = sess.run(loss, {x: X, y: Y})
assert l1 < l0 * 0.2, (l0, l1)
print("v1 train ok", l0, "->", l1)
bps.shutdown()
"""

_HOOK_V1 = r"""
import numpy as np
import tensorflow as tf
import byteps_tpu.tensorflow as bps
from byteps_tpu.tensorflow import v1 as bps_v1

bps.init()
g = tf.Graph()
with g.as_default():
    v = tf.compat.v1.get_variable(
        "v", [4], tf.float32,
        tf.compat.v1.constant_initializer(float(bps.rank() + 1)))
    hook = bps_v1.BroadcastGlobalVariablesHook(root_rank=0)
    hook.begin()
    with tf.compat.v1.Session() as sess:
        sess.run(tf.compat.v1.global_variables_initializer())
        hook.after_create_session(sess, None)
        out = sess.run(v)
# single worker: broadcast-from-root leaves root's value
assert np.allclose(out, 1.0), out
print("v1 hook ok", out)
bps.shutdown()
"""


def test_v1_optimizer_trains_mesh_tier():
    r = _run(_TRAIN_V1)
    assert r.returncode == 0, r.stdout[-2000:] + r.stderr[-2000:]
    assert "v1 train ok" in r.stdout


def test_v1_optimizer_trains_over_ps():
    """The same graph through a real loopback PS: compute_gradients'
    py_function hops land in the native client/server path."""
    sys.path.insert(0, REPO)
    from byteps_tpu.utils.net import free_port

    port = free_port()
    env = {"DMLC_NUM_WORKER": "1", "DMLC_NUM_SERVER": "1",
           "DMLC_PS_ROOT_URI": "127.0.0.1",
           "DMLC_PS_ROOT_PORT": str(port),
           "BYTEPS_FORCE_DISTRIBUTED": "1"}
    srv = subprocess.Popen(
        [sys.executable, "-m", "byteps_tpu.server"],
        env={**os.environ, **env, "JAX_PLATFORMS": "cpu",
             "PYTHONPATH": REPO + os.pathsep
             + os.environ.get("PYTHONPATH", "")},
        cwd=REPO, stdout=subprocess.DEVNULL, stderr=subprocess.STDOUT)
    try:
        r = _run(_TRAIN_V1, env_extra=env)
        assert r.returncode == 0, r.stdout[-2000:] + r.stderr[-2000:]
        assert "v1 train ok" in r.stdout
        srv.wait(timeout=30)
    finally:
        if srv.poll() is None:
            srv.kill()


def test_v1_broadcast_hook():
    r = _run(_HOOK_V1)
    assert r.returncode == 0, r.stdout[-2000:] + r.stderr[-2000:]
    assert "v1 hook ok" in r.stdout


_TRAIN_V1_ASYNC = r"""
import numpy as np
import tensorflow as tf
import byteps_tpu.tensorflow as bps
from byteps_tpu.tensorflow import v1 as bps_v1

bps.init()
from byteps_tpu.core.state import get_state
assert get_state().config.enable_async
assert get_state().ps_client is not None
g = tf.Graph()
with g.as_default():
    rng = np.random.RandomState(0)
    X = rng.randn(128, 8).astype(np.float32)
    Y = (X @ np.arange(8, dtype=np.float32)[:, None] * 0.1 + 0.5)
    x = tf.compat.v1.placeholder(tf.float32, [None, 8])
    y = tf.compat.v1.placeholder(tf.float32, [None, 1])
    w = tf.compat.v1.get_variable("w", [8, 1], tf.float32,
                                  tf.compat.v1.zeros_initializer())
    b = tf.compat.v1.get_variable("b", [1], tf.float32,
                                  tf.compat.v1.constant_initializer(7.0))
    loss = tf.reduce_mean(tf.square(x @ w + b - y))
    opt = bps_v1.DistributedOptimizer(
        tf.compat.v1.train.GradientDescentOptimizer(0.05))
    train_op = opt.minimize(loss)
    with tf.compat.v1.Session() as sess:
        sess.run(tf.compat.v1.global_variables_initializer())
        l0 = sess.run(loss, {x: X, y: Y})
        sess.run(train_op, {x: X, y: Y})
        b1 = float(sess.run(b)[0])
        # the async store is seeded with the INITIAL weights before the
        # first delta push: one small step must leave b near its 7.0
        # init. The zero-seeded-store bug made the pull return the bare
        # delta (~-0.65), collapsing b by ~7.
        assert abs(b1 - 7.0) < 2.0, b1
        for _ in range(80):
            sess.run(train_op, {x: X, y: Y})
        l1 = sess.run(loss, {x: X, y: Y})
assert l1 < l0 * 0.2, (l0, l1)
print("v1 async ok", l0, "->", l1)
bps.shutdown()
"""


def test_v1_async_delta_over_ps():
    """Async mode (BYTEPS_ENABLE_ASYNC) through a real async-mode PS:
    apply_gradients must seed the server's authoritative store with the
    initial weights before the first delta push — the generic push_pull
    path's zero init would make every pull return bare delta sums and
    silently destroy the model (round-4 review regression test)."""
    sys.path.insert(0, REPO)
    from byteps_tpu.utils.net import free_port

    port = free_port()
    env = {"DMLC_NUM_WORKER": "1", "DMLC_NUM_SERVER": "1",
           "DMLC_PS_ROOT_URI": "127.0.0.1",
           "DMLC_PS_ROOT_PORT": str(port),
           "BYTEPS_FORCE_DISTRIBUTED": "1",
           "BYTEPS_ENABLE_ASYNC": "1"}
    srv = subprocess.Popen(
        [sys.executable, "-m", "byteps_tpu.server"],
        env={**os.environ, **env, "JAX_PLATFORMS": "cpu",
             "PYTHONPATH": REPO + os.pathsep
             + os.environ.get("PYTHONPATH", "")},
        cwd=REPO, stdout=subprocess.DEVNULL, stderr=subprocess.STDOUT)
    try:
        r = _run(_TRAIN_V1_ASYNC, env_extra=env)
        assert r.returncode == 0, r.stdout[-2000:] + r.stderr[-2000:]
        assert "v1 async ok" in r.stdout
        srv.wait(timeout=30)
    finally:
        if srv.poll() is None:
            srv.kill()
