"""Persistent host staging arena (core/arena.py, BYTEPS_STAGING_ARENA):
slot reuse across rounds, versioned-checkout conflict fallback, the
zero-gradient-sized-allocation steady state of the PS train step
(asserted via the arena telemetry counters), fused-bucket slot reuse,
and arena-off numerics identical to arena-on."""

import contextlib
import os
import threading

import numpy as np
import optax
import pytest

from byteps_tpu.config import Config
from byteps_tpu.core.arena import StagingArena
from byteps_tpu.server import run_server

_PORT = [22400]


# --------------------------------------------------------------------- #
# unit tier: the arena itself
# --------------------------------------------------------------------- #


def _ptr(a: np.ndarray) -> int:
    return a.__array_interface__["data"][0]


def test_checkout_release_reuses_buffer():
    arena = StagingArena()
    lease = arena.checkout("k", 1024)
    p0 = _ptr(lease.buf)
    assert lease.buf.nbytes == 1024 and not lease.fresh
    assert p0 % 64 == 0, "slot not 64-byte aligned"
    lease.release()
    lease2 = arena.checkout("k", 1024)
    assert _ptr(lease2.buf) == p0, "slot not reused after release"
    lease2.release()
    s = arena.stats()
    assert s["slot_allocs"] == 1 and s["allocs_avoided"] == 1
    assert s["slots_live"] == 1 and s["bytes_pinned"] == 1024
    assert s["checkout_conflicts"] == 0 and s["fresh_allocs"] == 0


def test_checkout_conflict_falls_back_fresh():
    arena = StagingArena()
    held = arena.checkout("k", 256)
    other = arena.checkout("k", 256)  # round N+1 while N still writing
    assert other.fresh and _ptr(other.buf) != _ptr(held.buf)
    s = arena.stats()
    assert s["checkout_conflicts"] == 1 and s["fresh_allocs"] == 1
    other.release()  # no-op for fresh leases
    held.release()
    again = arena.checkout("k", 256)
    assert _ptr(again.buf) == _ptr(held.buf), "slot lost after conflict"


def test_resize_reallocates_and_release_is_version_guarded():
    arena = StagingArena()
    a = arena.checkout("k", 128)
    a.release()
    b = arena.checkout("k", 512)  # size change: slot dropped + realloc
    assert b.buf.nbytes == 512
    assert arena.stats()["resizes"] == 1
    # a stale release of the retired lease must not free the NEW slot
    a.release()
    c = arena.checkout("k", 512)
    assert c.fresh, "stale release unlocked a live slot"


def test_abandon_drops_slot():
    arena = StagingArena()
    lease = arena.checkout("k", 64)
    p0 = _ptr(lease.buf)
    lease.abandon()
    assert arena.stats()["slots_live"] == 0
    fresh = arena.checkout("k", 64)
    assert not fresh.fresh  # new tracked slot under the same key
    assert arena.stats()["slot_allocs"] == 2
    del p0


def test_disabled_arena_hands_out_fresh_untracked():
    arena = StagingArena(enabled=False)
    a = arena.checkout("k", 64)
    a.release()
    b = arena.checkout("k", 64)
    assert a.fresh and b.fresh
    s = arena.stats()
    assert s["slots_live"] == 0 and s["fresh_allocs"] == 2
    assert s["slot_allocs"] == 0


def test_invalidate_prefix_drops_free_slots_only():
    arena = StagingArena()
    arena.checkout("grad/w:out", 64).release()
    busy = arena.checkout("grad/w:in", 64)
    arena.checkout("grad/b:out", 64).release()
    arena.checkout("grad/w2:out", 64).release()  # sibling w2 vs w
    # the registry invalidates with a ":" terminator so a re-partition
    # of "grad/w" never clips sibling tensors sharing the name prefix
    arena.invalidate_prefix("grad/w:")
    keys = arena.slot_keys()
    assert "grad/w:out" not in keys
    assert "grad/w:in" in keys      # busy: left for its lease
    assert "grad/b:out" in keys     # other prefix untouched
    assert "grad/w2:out" in keys    # sibling untouched
    busy.release()


# --------------------------------------------------------------------- #
# integration tier: the PS train step over a loopback server
# --------------------------------------------------------------------- #


@contextlib.contextmanager
def _ps_env(arena: str = None, extra_env: dict = None):
    from byteps_tpu.core.state import GlobalState

    port = _PORT[0]
    _PORT[0] += 1
    env = {
        "DMLC_NUM_WORKER": "1", "DMLC_NUM_SERVER": "1",
        "DMLC_PS_ROOT_URI": "127.0.0.1", "DMLC_PS_ROOT_PORT": str(port),
        "BYTEPS_FORCE_DISTRIBUTED": "1", **(extra_env or {}),
    }
    if arena is not None:
        env["BYTEPS_STAGING_ARENA"] = arena
    saved = {k: os.environ.get(k) for k in env}
    os.environ.update(env)
    server = threading.Thread(
        target=run_server,
        args=(port, Config(num_workers=1, num_servers=1)), daemon=True)
    server.start()
    GlobalState._instance = None
    import byteps_tpu as bps
    bps.init()
    try:
        yield bps
    finally:
        bps.shutdown()
        server.join(timeout=10)
        GlobalState._instance = None
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def _mlp_setup():
    import jax
    import jax.numpy as jnp

    from byteps_tpu.models import mlp

    cfg = mlp.MLPConfig(in_dim=64, hidden=(32, 32), n_classes=10)
    params = mlp.init_params(jax.random.PRNGKey(0), cfg)
    rng = np.random.RandomState(0)
    batch = {"x": jnp.asarray(rng.rand(32, 64), jnp.float32),
             "y": jnp.asarray(rng.randint(0, 10, 32), jnp.int32)}
    return cfg, params, batch


def _run_steps(bps, params, batch, cfg, steps=5, hook=None, **kw):
    import jax
    import jax.numpy as jnp

    from byteps_tpu.core.state import get_state
    from byteps_tpu.jax.train import make_ps_train_step
    from byteps_tpu.models import mlp

    params = jax.tree.map(jnp.array, params)  # private copy (donation)
    tx = optax.sgd(0.05)
    opt = tx.init(params)
    step = make_ps_train_step(lambda p, b: mlp.loss_fn(p, b, cfg), tx,
                              get_state().mesh, **kw)
    for i in range(steps):
        if hook is not None:
            hook(i)
        params, opt, loss = step(params, opt, batch)
    return jax.tree_util.tree_leaves(params), float(loss)


def test_steady_state_zero_gradient_sized_allocs():
    """The acceptance criterion: after warmup, the PS train step
    allocates NO gradient-sized host staging — every round is served
    from the persistent slots (allocs_avoided grows, slot_allocs and
    bytes_pinned flat, zero conflicts/fresh fallbacks)."""
    cfg, params, batch = _mlp_setup()
    with _ps_env(arena="1") as bps:
        import jax
        import jax.numpy as jnp

        from byteps_tpu.core.state import get_state
        from byteps_tpu.jax.train import make_ps_train_step
        from byteps_tpu.models import mlp

        params = jax.tree.map(jnp.array, params)
        tx = optax.sgd(0.05)
        opt = tx.init(params)
        step = make_ps_train_step(
            lambda p, b: mlp.loss_fn(p, b, cfg), tx, get_state().mesh)
        for _ in range(2):  # warmup: declarations, init-push, slot allocs
            params, opt, loss = step(params, opt, batch)
        warm = bps.get_arena_stats()
        assert warm["enabled"] and warm["slots_live"] > 0
        for _ in range(3):
            params, opt, loss = step(params, opt, batch)
        steady = bps.get_arena_stats()
        assert steady["slot_allocs"] == warm["slot_allocs"], \
            "steady state allocated new staging slots"
        assert steady["bytes_pinned"] == warm["bytes_pinned"]
        assert steady["checkout_conflicts"] == 0
        assert steady["fresh_allocs"] == 0
        # every step reuses every slot once
        assert steady["allocs_avoided"] >= \
            warm["allocs_avoided"] + 3 * warm["slots_live"]
        assert np.isfinite(loss)


def test_fused_bucket_slot_reused():
    """The fused bucket concatenates into a persistent arena slot (the
    np.concatenate-per-step allocation is gone): a fused/<digest>:in
    slot exists and is reused across rounds."""
    cfg, params, batch = _mlp_setup()
    with _ps_env(arena="1") as bps:
        _run_steps(bps, params, batch, cfg, steps=3)
        from byteps_tpu.core.state import get_state
        keys = get_state().arena.slot_keys()
        fused_in = [k for k in keys
                    if k.startswith("fused/") and k.endswith(":in")]
        fused_out = [k for k in keys
                     if k.startswith("fused/") and k.endswith(":out")]
        assert fused_in and fused_out, keys
        stats = bps.get_arena_stats()
        assert stats["allocs_avoided"] >= 2 * len(fused_in)
        assert stats["checkout_conflicts"] == 0


def test_checkout_conflict_still_trains_correctly():
    """Versioned checkout: leases held across a whole step (simulating a
    straggler pull still writing into last round's slots) force every
    checkout into the fresh-fallback path — results must be identical
    anyway, with the conflicts visible in telemetry."""
    import jax

    cfg, params, batch = _mlp_setup()
    with _ps_env(arena="1") as bps:
        from byteps_tpu.core.state import get_state

        held = []

        def hog(step_i):
            # after the slots exist, hold ALL of them through the step
            for lease in held:
                lease.release()
            held.clear()
            arena = get_state().arena
            for k in arena.slot_keys():
                slot = arena._slots.get(k)
                if slot is not None:
                    held.append(arena.checkout(k, slot.buf.nbytes))

        got, _ = _run_steps(bps, params, batch, cfg, steps=5, hook=hog)
        for lease in held:
            lease.release()
        stats = bps.get_arena_stats()
        assert stats["checkout_conflicts"] > 0, \
            "interference produced no conflicts — test is vacuous"

    # reference: plain local jit training (as test_fusion does)
    import optax as ox

    from byteps_tpu.models import mlp

    tx = ox.sgd(0.05)
    p, o = params, tx.init(params)

    def local(p, o, b):
        loss, g = jax.value_and_grad(lambda q: mlp.loss_fn(q, b, cfg))(p)
        u, o = tx.update(g, o, p)
        return ox.apply_updates(p, u), o, loss

    lj = jax.jit(local)
    for _ in range(5):
        p, o, _ = lj(p, o, batch)
    for a, b in zip(got, jax.tree_util.tree_leaves(p)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-6)


@pytest.mark.parametrize("compression", [None,
                                         {"compressor": "onebit",
                                          "ef": "vanilla"}],
                         ids=["dense", "onebit"])
def test_arena_off_numerics_identical(compression):
    """BYTEPS_STAGING_ARENA=0 must be bit-identical to arena-on: the
    arena only changes WHERE bytes are staged, never what is computed.
    Covered for the dense fused path and the host codec tier (which
    exercises the scheduler's arena-backed reply scratch)."""
    cfg, params, batch = _mlp_setup()
    kw = {}
    if compression is not None:
        kw = dict(compression=compression, min_compress_bytes=0,
                  device_compress=False)
    with _ps_env(arena="1") as bps:
        on, _ = _run_steps(bps, params, batch, cfg, steps=4, **kw)
        assert bps.get_arena_stats()["allocs_avoided"] > 0
    with _ps_env(arena="0") as bps:
        off, _ = _run_steps(bps, params, batch, cfg, steps=4, **kw)
        assert bps.get_arena_stats()["slots_live"] == 0
    for a, b in zip(on, off):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_handle_done_callback_orders_drain():
    """Handle.add_done_callback (the completion-ordered IMPORT's
    notification primitive): fires on completion, fires immediately for
    an already-done handle, and never re-fires."""
    from byteps_tpu.core.scheduler import Handle

    h = Handle(0, "t")
    fired = []
    h.add_done_callback(lambda: fired.append("a"))
    assert fired == []
    h._finish(np.zeros(1), None)
    assert fired == ["a"]
    h.add_done_callback(lambda: fired.append("b"))  # already done
    assert fired == ["a", "b"]
