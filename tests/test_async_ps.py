"""Asynchronous data parallelism (BYTEPS_ENABLE_ASYNC): server folds weight
deltas straight into the authoritative weights with no aggregation barrier,
pulls are always answerable (reference: server.cc:315-319,434-436;
torch/__init__.py:188-216)."""

import threading

import numpy as np
import optax
import pytest

from byteps_tpu.config import Config
from byteps_tpu.core.registry import TensorRegistry
from byteps_tpu.core.types import DataType
from byteps_tpu.server import run_server
from byteps_tpu.server.client import PSClient

_PORT = [20300]


def _start_async_server(port, num_workers):
    server = threading.Thread(
        target=run_server,
        args=(port, Config(num_workers=num_workers, num_servers=1,
                           enable_async=True)),
        daemon=True)
    server.start()
    return server


def test_async_protocol_two_workers():
    """Two workers seed the same initial weights, push deltas without any
    round barrier; every pull reflects all deltas folded so far."""
    port = _PORT[0]
    _PORT[0] += 1
    server = _start_async_server(port, num_workers=2)
    reg = TensorRegistry(Config(num_workers=2, num_servers=1))
    ctx = reg.init_tensor("w", 64 * 4, DataType.FLOAT32)
    w0 = np.arange(64, dtype=np.float32)

    c0 = PSClient([f"127.0.0.1:{port}"], worker_id=0)
    c1 = PSClient([f"127.0.0.1:{port}"], worker_id=1)
    try:
        # init barrier: both workers must seed before either proceeds
        t = threading.Thread(target=c1.init_weights, args=(ctx, w0.copy()))
        t.start()
        c0.init_weights(ctx, w0.copy())
        t.join(timeout=10)
        assert not t.is_alive()

        d0 = np.full(64, 0.5, np.float32)
        out0 = c0.push_delta_pull_weights(ctx, d0)
        np.testing.assert_allclose(out0, w0 + 0.5)   # no barrier on w1
        d1 = np.full(64, 0.25, np.float32)
        out1 = c1.push_delta_pull_weights(ctx, d1)
        np.testing.assert_allclose(out1, w0 + 0.75)  # both deltas folded
        # worker 0 pushes again immediately — async never parks
        out0b = c0.push_delta_pull_weights(ctx, d0)
        np.testing.assert_allclose(out0b, w0 + 1.25)
    finally:
        c0.close(shutdown_servers=True)
        c1.close(shutdown_servers=True)
        server.join(timeout=10)


@pytest.fixture()
def async_env(monkeypatch):
    from byteps_tpu.core.state import GlobalState

    port = _PORT[0]
    _PORT[0] += 1
    monkeypatch.setenv("DMLC_NUM_WORKER", "1")
    monkeypatch.setenv("DMLC_NUM_SERVER", "1")
    monkeypatch.setenv("DMLC_PS_ROOT_URI", "127.0.0.1")
    monkeypatch.setenv("DMLC_PS_ROOT_PORT", str(port))
    monkeypatch.setenv("BYTEPS_FORCE_DISTRIBUTED", "1")
    monkeypatch.setenv("BYTEPS_ENABLE_ASYNC", "1")
    server = _start_async_server(port, num_workers=1)

    GlobalState._instance = None
    import byteps_tpu as bps
    bps.init()
    yield bps
    bps.shutdown()
    server.join(timeout=10)
    GlobalState._instance = None


def test_async_train_step(async_env):
    import jax
    from byteps_tpu.core.state import get_state
    from byteps_tpu.jax.train import make_async_ps_train_step
    from byteps_tpu.models import mlp

    assert get_state().config.enable_async
    mesh = get_state().mesh
    cfg = mlp.MLPConfig(in_dim=32, hidden=(16,), n_classes=4)
    params = mlp.init_params(jax.random.PRNGKey(0), cfg)
    tx = optax.sgd(0.1)
    step = make_async_ps_train_step(
        lambda p, b: mlp.loss_fn(p, b, cfg), tx, mesh)
    opt = tx.init(params)
    rng = np.random.RandomState(0)
    x = rng.randn(128, 32).astype(np.float32)
    y = np.argmax(x @ rng.randn(32, 4), -1).astype(np.int32)
    losses = []
    for _ in range(15):
        params, opt, loss = step(params, opt, {"x": x, "y": y})
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.8, (losses[0], losses[-1])


def test_async_step_without_ps(bps):
    """No PS configured: the async step degrades to local SGD."""
    import jax
    from byteps_tpu.core.state import get_state
    from byteps_tpu.jax.train import make_async_ps_train_step
    from byteps_tpu.models import mlp

    assert get_state().ps_client is None
    mesh = get_state().mesh
    cfg = mlp.MLPConfig(in_dim=8, hidden=(8,), n_classes=3)
    params = mlp.init_params(jax.random.PRNGKey(0), cfg)
    tx = optax.sgd(0.1)
    step = make_async_ps_train_step(
        lambda p, b: mlp.loss_fn(p, b, cfg), tx, mesh)
    opt = tx.init(params)
    rng = np.random.RandomState(1)
    x = rng.randn(64, 8).astype(np.float32)
    y = rng.randint(0, 3, 64).astype(np.int32)
    l0 = None
    for _ in range(10):
        params, opt, loss = step(params, opt, {"x": x, "y": y})
        l0 = l0 or float(loss)
    assert float(loss) < l0
