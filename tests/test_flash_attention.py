"""Flash/blockwise attention vs the dense reference (models/llama.py
_attention math). Exactness needs fp32 matmul precision on CPU —
without it, bf16-defaulted matmuls drift ~1e-2 and mask algorithm bugs
(project verify notes)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from byteps_tpu.ops.flash_attention import (
    _flash_fwd, blockwise_attention, flash_attention, make_flash_attn,
)


def _dense(q, k, v, causal=True):
    B, S, H, D = q.shape
    groups = H // k.shape[2]
    k = jnp.repeat(k, groups, axis=2)
    v = jnp.repeat(v, groups, axis=2)
    logits = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                        k.astype(jnp.float32)) / np.sqrt(D)
    if causal:
        mask = jnp.tril(jnp.ones((S, S), bool))
        logits = jnp.where(mask[None, None], logits, -1e30)
    p = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p,
                      v.astype(jnp.float32)).astype(q.dtype)


def _qkv(B=2, S=256, H=4, Hkv=2, D=64, seed=0, dtype=jnp.float32):
    rng = np.random.RandomState(seed)
    q = jnp.asarray(rng.randn(B, S, H, D), dtype)
    k = jnp.asarray(rng.randn(B, S, Hkv, D), dtype)
    v = jnp.asarray(rng.randn(B, S, Hkv, D), dtype)
    return q, k, v


@pytest.mark.parametrize("causal", [True, False])
def test_blockwise_matches_dense(causal):
    q, k, v = _qkv()
    with jax.default_matmul_precision("float32"):
        want = _dense(q, k, v, causal)
        got = blockwise_attention(q, k, v, causal=causal, block_k=64)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_blockwise_gradients_match_dense():
    q, k, v = _qkv(S=128, D=32)

    with jax.default_matmul_precision("float32"):
        def loss_dense(q, k, v):
            return jnp.sum(jnp.square(_dense(q, k, v)))

        def loss_blk(q, k, v):
            return jnp.sum(jnp.square(
                blockwise_attention(q, k, v, block_k=32)))

        gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
        gb = jax.grad(loss_blk, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gb, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-4, atol=5e-4)


@pytest.mark.parametrize("causal", [True, False])
def test_pallas_kernel_matches_dense_interpret(causal):
    """The TPU kernel's math, run through the Pallas interpreter on CPU:
    same online-softmax result as the dense reference, including the
    causal block-skip and GQA head mapping."""
    q, k, v = _qkv(B=1, S=256, H=4, Hkv=2, D=64, seed=3)
    with jax.default_matmul_precision("float32"):
        want = _dense(q, k, v, causal)
        got = _flash_fwd(q, k, v, causal, block_q=64, block_k=64,
                         interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_flash_attention_falls_back_and_differentiates():
    """Off-TPU flash_attention runs the blockwise path; custom_vjp
    gradients flow and match dense."""
    q, k, v = _qkv(S=128, D=32, seed=5)
    with jax.default_matmul_precision("float32"):
        out = flash_attention(q, k, v, True, 64, 64)
        want = _dense(q, k, v, True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(want),
                                   rtol=2e-5, atol=2e-5)
        g = jax.grad(lambda q_: jnp.sum(
            flash_attention(q_, k, v, True, 64, 64) ** 2))(q)
        gd = jax.grad(lambda q_: jnp.sum(_dense(q_, k, v) ** 2))(q)
    np.testing.assert_allclose(np.asarray(g), np.asarray(gd),
                               rtol=5e-4, atol=5e-4)


def test_llama_forward_with_flash_impl():
    """attn_impl seam: the llama forward with the blockwise impl equals
    the default dense attention."""
    from byteps_tpu.models import llama

    cfg = llama.LlamaConfig.tiny(vocab_size=64, seq=64)
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    tokens = jnp.asarray(
        np.random.RandomState(0).randint(0, 64, (2, 64)), jnp.int32)
    with jax.default_matmul_precision("float32"):
        dense = llama.forward(params, tokens, cfg)
        flash = llama.forward(params, tokens, cfg,
                              attn_impl=make_flash_attn(block_q=32,
                                                        block_k=32))
    # the model computes in bf16 (eps 0.39%): per-op rounding differs
    # between the two attention orders and compounds over layers — an
    # algorithmic error (wrong mask/normalizer) would be O(1), not %
    np.testing.assert_allclose(np.asarray(flash, np.float32),
                               np.asarray(dense, np.float32),
                               rtol=0.06, atol=0.06)
