"""BERT over the PS path — the BASELINE config-3 headline vehicle
(reference README.md:34-40: BERT-large ~90% scaling at 256 GPUs) given a
test vehicle at tiny dims: MLM training through make_ps_train_step must
converge, with and without wire compression, and the examples/benchmark.py
--model bert smoke must run. The dryrun side lives in
__graft_entry__._dryrun_bert_dp_tp (dp x tp Megatron layout)."""

import os
import subprocess
import sys
import threading

import numpy as np
import optax
import pytest

from byteps_tpu.config import Config
from byteps_tpu.server import run_server

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_PORT = [20800]


@pytest.fixture()
def ps_env(monkeypatch):
    """One worker + one server on loopback, force-distributed (the
    test_ps_integration pattern)."""
    from byteps_tpu.core.state import GlobalState

    port = _PORT[0]
    _PORT[0] += 1
    monkeypatch.setenv("DMLC_NUM_WORKER", "1")
    monkeypatch.setenv("DMLC_NUM_SERVER", "1")
    monkeypatch.setenv("DMLC_PS_ROOT_URI", "127.0.0.1")
    monkeypatch.setenv("DMLC_PS_ROOT_PORT", str(port))
    monkeypatch.setenv("BYTEPS_FORCE_DISTRIBUTED", "1")
    server = threading.Thread(
        target=run_server,
        args=(port, Config(num_workers=1, num_servers=1)), daemon=True)
    server.start()

    GlobalState._instance = None
    import byteps_tpu as bps
    bps.init()
    yield bps
    bps.shutdown()
    server.join(timeout=10)
    GlobalState._instance = None


def _mlm_batch(cfg, B=8, seed=0):
    import jax.numpy as jnp

    rng = np.random.RandomState(seed)
    tokens = rng.randint(0, cfg.vocab_size, (B, cfg.max_seq_len))
    labels = np.where(rng.rand(B, cfg.max_seq_len) < 0.15, tokens, -100)
    return {"tokens": jnp.asarray(tokens, jnp.int32),
            "labels": jnp.asarray(labels, jnp.int32)}


def _train_bert(ps_env, steps=12, **ps_kwargs):
    import jax
    from byteps_tpu.core.state import get_state
    from byteps_tpu.jax.train import make_ps_train_step
    from byteps_tpu.models import bert

    cfg = bert.BertConfig.tiny(vocab_size=64, seq=16)
    params = bert.init_params(jax.random.PRNGKey(0), cfg)
    tx = optax.adam(2e-3)
    opt = tx.init(params)
    step = make_ps_train_step(
        lambda p, b: bert.loss_fn(p, b, cfg), tx, get_state().mesh,
        **ps_kwargs)
    batch = _mlm_batch(cfg)
    losses = []
    for _ in range(steps):
        params, opt, loss = step(params, opt, batch)
        losses.append(float(loss))
    return losses


def test_bert_trains_over_ps(ps_env):
    losses = _train_bert(ps_env)
    assert np.isfinite(losses).all(), losses
    assert losses[-1] < losses[0] * 0.7, losses


def test_bert_trains_over_ps_compressed(ps_env):
    """BASELINE config 4 shape (compressed wire) on the BERT vehicle —
    host codec tier so the numpy/native codec stack is what runs."""
    losses = _train_bert(
        ps_env, compression={"compressor": "onebit", "ef": "vanilla"},
        min_compress_bytes=0, device_compress=False)
    assert np.isfinite(losses).all(), losses
    assert losses[-1] < losses[0] * 0.8, losses


def test_benchmark_bert_smoke():
    """examples/benchmark.py --model bert runs end-to-end (the
    reference-format synthetic throughput vehicle)."""
    pin = ("from byteps_tpu.utils.jax_compat import force_cpu; "
           "force_cpu(8); "
           "import runpy, sys; sys.argv = sys.argv[1:]; "
           "runpy.run_path(sys.argv[0], run_name='__main__')")
    r = subprocess.run(
        [sys.executable, "-c", pin,
         os.path.join(REPO, "examples", "benchmark.py"),
         "--model", "bert", "--tiny", "--num-iters", "2",
         "--num-warmup-batches", "1", "--batch-size", "8"],
        cwd=REPO, capture_output=True, text=True, timeout=420,
        env={**os.environ, "PYTHONPATH":
             REPO + os.pathsep + os.environ.get("PYTHONPATH", "")})
    assert r.returncode == 0, r.stdout[-2000:] + r.stderr[-2000:]
    assert "img/sec" in r.stdout or "examples/sec" in r.stdout or \
        "Total img/sec" in r.stdout, r.stdout[-800:]
