"""Pallas kernel parity tests (interpret mode on the CPU mesh; the compiled
path is exercised on real TPU by bench/verify runs)."""

import jax.numpy as jnp
import numpy as np
import pytest

from byteps_tpu.ops.compression.pallas_kernels import (
    onebit_pack, onebit_unpack,
)


@pytest.mark.parametrize("n", [100, 32768, 40000])
def test_onebit_pallas_roundtrip(n):
    x = np.random.RandomState(n).randn(n).astype(np.float32)
    bits = onebit_pack(jnp.asarray(x), True)
    out = np.asarray(onebit_unpack(bits, jnp.float32(2.5), n, True))
    golden = np.where(x >= 0, 2.5, -2.5).astype(np.float32)
    np.testing.assert_allclose(out, golden)


def test_onebit_pallas_all_negative():
    x = -np.ones(1000, np.float32)
    bits = onebit_pack(jnp.asarray(x), True)
    out = np.asarray(onebit_unpack(bits, jnp.float32(1.0), 1000, True))
    np.testing.assert_allclose(out, x)


from byteps_tpu.ops.compression.pallas_kernels import (  # noqa: E402
    dithering_levels, randomk_indices,
)
from byteps_tpu.ops.compression.codecs import (  # noqa: E402
    DitheringCodec, RandomkCodec,
)
from byteps_tpu.ops.compression.rng import (  # noqa: E402
    np_uniform_parallel, uniform_base,
)


def _base(seed, step):
    return jnp.asarray(uniform_base(seed, step))


@pytest.mark.parametrize("n", [100, 4096, 50000])
@pytest.mark.parametrize("step", [0, 7])
def test_dithering_linear_pallas_bit_parity(n, step):
    """Fused kernel levels == the jnp codec's levels bit-for-bit (both use
    the same counter RNG and op order)."""
    x = np.random.RandomState(n + step).randn(n).astype(np.float32)
    codec = DitheringCodec(size=n, s=64, seed=11, use_pallas=False)
    want = np.asarray(codec.compress(jnp.asarray(x), step=step)["levels"])
    norm = jnp.maximum(jnp.max(jnp.abs(jnp.asarray(x))), 1e-30)
    got = np.asarray(dithering_levels(
        jnp.asarray(x), norm, _base(11, step), 64, "linear", True))
    np.testing.assert_array_equal(got, want)


def test_dithering_natural_pallas_parity():
    """Natural partition: powers-of-two levels; interpret mode shares
    XLA's transcendentals with the jnp path, so levels match exactly."""
    n = 3000
    x = np.random.RandomState(3).randn(n).astype(np.float32)
    codec = DitheringCodec(size=n, s=64, seed=5, partition="natural",
                           use_pallas=False)
    want = np.asarray(codec.compress(jnp.asarray(x), step=2)["levels"])
    norm = jnp.maximum(jnp.max(jnp.abs(jnp.asarray(x))), 1e-30)
    got = np.asarray(dithering_levels(
        jnp.asarray(x), norm, _base(5, 2), 64, "natural", True))
    exact = (got == want).mean()
    assert exact >= 0.999, exact  # ulp slack at log2 boundaries


@pytest.mark.parametrize("k,size", [(32, 512), (1000, 1 << 20)])
def test_randomk_indices_pallas_bit_parity(k, size):
    """Kernel indices == RandomkCodec._indices == numpy golden."""
    codec = RandomkCodec(size=size, k=k, seed=7, use_pallas=False)
    for step in (0, 3):
        want = np.asarray(codec._indices(step))
        got = np.asarray(randomk_indices(
            _base(7, step), jnp.int32(size), k, True))
        np.testing.assert_array_equal(got, want)
        # and against the numpy golden model directly
        from byteps_tpu.ops.compression.rng import np_index_parallel
        gold = np_index_parallel(7, k, size, mix=step)
        np.testing.assert_array_equal(got, gold)


def test_dithering_codec_roundtrip_quality_pallas_kernel():
    """decompress(kernel levels) is a valid unbiased-ish quantization of x
    (sanity on the full codec path with the kernel payload)."""
    n = 8192
    x = np.random.RandomState(0).randn(n).astype(np.float32)
    codec = DitheringCodec(size=n, s=64, seed=1, use_pallas=False)
    norm = jnp.maximum(jnp.max(jnp.abs(jnp.asarray(x))), 1e-30)
    levels = dithering_levels(jnp.asarray(x), norm, _base(1, 0), 64,
                              "linear", True)
    out = np.asarray(codec.decompress(
        {"levels": levels, "norm": np.float32(norm)}))
    err = np.abs(out - x)
    assert err.max() <= float(norm) / 64 + 1e-6
