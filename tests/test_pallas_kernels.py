"""Pallas kernel parity tests (interpret mode on the CPU mesh; the compiled
path is exercised on real TPU by bench/verify runs)."""

import jax.numpy as jnp
import numpy as np
import pytest

from byteps_tpu.ops.compression.pallas_kernels import (
    onebit_pack, onebit_unpack,
)


@pytest.mark.parametrize("n", [100, 32768, 40000])
def test_onebit_pallas_roundtrip(n):
    x = np.random.RandomState(n).randn(n).astype(np.float32)
    bits = onebit_pack(jnp.asarray(x), True)
    out = np.asarray(onebit_unpack(bits, jnp.float32(2.5), n, True))
    golden = np.where(x >= 0, 2.5, -2.5).astype(np.float32)
    np.testing.assert_allclose(out, golden)


def test_onebit_pallas_all_negative():
    x = -np.ones(1000, np.float32)
    bits = onebit_pack(jnp.asarray(x), True)
    out = np.asarray(onebit_unpack(bits, jnp.float32(1.0), 1000, True))
    np.testing.assert_allclose(out, x)
