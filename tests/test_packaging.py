"""Packaging surface: the repo is pip-installable and ships the
``bpslaunch`` console script (reference setup.py entry_points parity).
A real venv (system-site-packages for the preinstalled jax stack) does an
offline ``pip install -e .`` and runs ``bpslaunch --help``."""

import os
import site
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# The test venv is created from THIS interpreter, itself usually a venv
# (--system-site-packages only chains to the base python): expose the
# running env's site-packages (setuptools for the offline build; jax etc.
# for the import check) explicitly.
_SITE = os.pathsep.join(site.getsitepackages())
_ENV = {**os.environ, "PIP_NO_INPUT": "1", "PYTHONPATH": _SITE}


@pytest.fixture(scope="module")
def venv(tmp_path_factory):
    vdir = tmp_path_factory.mktemp("pkg") / "venv"
    r = subprocess.run(
        [sys.executable, "-m", "venv", str(vdir)],
        capture_output=True, text=True, timeout=300)
    assert r.returncode == 0, r.stderr[-2000:]
    return vdir


def test_editable_install_and_bpslaunch(venv):
    pip = venv / "bin" / "pip"
    # --no-build-isolation: offline build against the exposed setuptools
    r = subprocess.run(
        [str(pip), "install", "--no-build-isolation", "--no-deps", "-e",
         REPO],
        capture_output=True, text=True, timeout=600, env=_ENV)
    assert r.returncode == 0, r.stdout[-2000:] + r.stderr[-2000:]

    bpslaunch = venv / "bin" / "bpslaunch"
    assert bpslaunch.exists(), "console script not installed"
    r = subprocess.run([str(bpslaunch), "--help"], capture_output=True,
                       text=True, timeout=120, env=_ENV)
    assert r.returncode == 0, r.stdout[-1000:] + r.stderr[-1000:]
    assert "bpslaunch" in (r.stdout + r.stderr).lower() or \
        "usage" in (r.stdout + r.stderr).lower(), r.stdout[-500:]

    # the installed package resolves and exposes the public API
    py = venv / "bin" / "python"
    r = subprocess.run(
        [str(py), "-c",
         "import byteps_tpu, byteps_tpu.launcher; "
         "print(byteps_tpu.__name__, callable(byteps_tpu.launcher.main))"],
        capture_output=True, text=True, timeout=120, cwd=str(venv),
        env=_ENV)
    assert r.returncode == 0, r.stdout[-1000:] + r.stderr[-1000:]
    assert "byteps_tpu True" in r.stdout
