"""Ring attention correctness: must match dense causal attention exactly
(it's an exact algorithm, not an approximation), including GQA, and compose
with the Llama forward under sequence sharding."""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

from byteps_tpu.core.state import get_state
from byteps_tpu.models import llama
from byteps_tpu.parallel.ring_attention import make_ring_attn, ring_attention


def dense_causal(q, k, v):
    B, S, H, D = q.shape
    groups = H // k.shape[2]
    if groups > 1:
        k = jnp.repeat(k, groups, axis=2)
        v = jnp.repeat(v, groups, axis=2)
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) / np.sqrt(D)
    mask = jnp.tril(jnp.ones((S, S), bool))
    scores = jnp.where(mask[None, None], scores, -1e30)
    p = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v)


@pytest.mark.parametrize("hkv", [8, 2])   # MHA and GQA
@pytest.mark.parametrize("causal", [True, False])
def test_ring_matches_dense(bps, hkv, causal):
    mesh = get_state().mesh      # 8 devices on "dp"; reuse as the ring axis
    B, S, H, D = 2, 64, 8, 16
    rng = np.random.RandomState(0)
    q = rng.randn(B, S, H, D).astype(np.float32)
    k = rng.randn(B, S, hkv, D).astype(np.float32)
    v = rng.randn(B, S, hkv, D).astype(np.float32)

    if causal:
        ref = dense_causal(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v))
    else:
        kk = jnp.repeat(jnp.asarray(k), H // hkv, axis=2)
        vv = jnp.repeat(jnp.asarray(v), H // hkv, axis=2)
        scores = jnp.einsum("bqhd,bkhd->bhqk", jnp.asarray(q), kk) / np.sqrt(D)
        p = jax.nn.softmax(scores, axis=-1)
        ref = jnp.einsum("bhqk,bkhd->bqhd", p, vv)

    ring = jax.jit(jax.shard_map(
        functools.partial(ring_attention, axis="dp", causal=causal),
        mesh=mesh,
        in_specs=(P(None, "dp"), P(None, "dp"), P(None, "dp")),
        out_specs=P(None, "dp"), check_vma=False))
    out = ring(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_llama_forward_sp_matches_dense(bps):
    """Llama forward with sequence sharded over the mesh == unsharded."""
    mesh = get_state().mesh
    cfg = llama.LlamaConfig.tiny(vocab_size=64, seq=64)
    # fp32 for exact comparison
    import dataclasses
    cfg = dataclasses.replace(cfg, dtype=jnp.float32)
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    tokens = jnp.asarray(
        np.random.RandomState(0).randint(0, 64, (2, 64)), jnp.int32)

    ref = llama.forward(params, tokens, cfg)

    fwd_sp = jax.jit(jax.shard_map(
        lambda p, t: llama.forward(p, t, cfg,
                                   attn_impl=make_ring_attn(axis="dp"),
                                   sp_axis="dp"),
        mesh=mesh, in_specs=(P(), P(None, "dp")), out_specs=P(None, "dp"),
        check_vma=False))
    out = fwd_sp(params, tokens)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=3e-4, atol=3e-4)


def test_llama_sp_trains(bps):
    """End-to-end: tiny llama trains with ring attention + sequence
    sharding (loss decreases)."""
    import dataclasses
    mesh = get_state().mesh
    cfg = dataclasses.replace(llama.LlamaConfig.tiny(vocab_size=32, seq=64),
                              dtype=jnp.float32)
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    tx = optax.adam(1e-2)
    opt = tx.init(params)

    def local_loss(p, b):
        return llama.loss_fn(p, b, cfg, attn_impl=make_ring_attn(axis="dp"),
                             sp_axis="dp")

    def step(p, o, b):
        loss, g = jax.value_and_grad(local_loss)(p, b)
        # grads already identical across sp (pmean'd loss); adam update
        u, o = tx.update(g, o, p)
        return optax.apply_updates(p, u), o, loss

    stepj = jax.jit(jax.shard_map(
        step, mesh=mesh,
        in_specs=(P(), P(), P(None, "dp")),
        out_specs=(P(), P(), P()), check_vma=False))

    seq = (np.arange(65)[None, :] + np.arange(4)[:, None]) % 13
    batch = {"inputs": jnp.asarray(seq[:, :-1], jnp.int32),
             "targets": jnp.asarray(seq[:, 1:], jnp.int32)}
    losses = []
    for _ in range(25):
        params, opt, loss = stepj(params, opt, batch)
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.6, (losses[0], losses[-1])


def test_llama_sp_chunked_xent_matches_dense_loss(bps):
    """cfg.xent_chunks composes with sequence parallelism: the chunked
    loss under sp sharding (ring attention, pre-shifted batch) equals
    the unsharded dense loss — the pmean-of-local-means reduction is
    identical on both loss paths."""
    import dataclasses
    mesh = get_state().mesh
    cfg = dataclasses.replace(llama.LlamaConfig.tiny(vocab_size=64, seq=64),
                              dtype=jnp.float32)
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    tokens = np.random.RandomState(0).randint(0, 64, (2, 65))
    batch_full = {"tokens": jnp.asarray(tokens, jnp.int32)}
    ref = llama.loss_fn(params, batch_full, cfg)

    cfg_ck = dataclasses.replace(cfg, xent_chunks=4)
    sharded = {"inputs": jnp.asarray(tokens[:, :-1], jnp.int32),
               "targets": jnp.asarray(tokens[:, 1:], jnp.int32)}
    loss_sp = jax.jit(jax.shard_map(
        lambda p, b: llama.loss_fn(p, b, cfg_ck,
                                   attn_impl=make_ring_attn(axis="dp"),
                                   sp_axis="dp"),
        mesh=mesh, in_specs=(P(), P(None, "dp")), out_specs=P(),
        check_vma=False))
    got = loss_sp(params, sharded)
    np.testing.assert_allclose(float(got), float(ref), rtol=5e-4)
