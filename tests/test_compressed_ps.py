"""Compressed DCN push_pull: worker host codecs <-> C++ server mirror.

The reference tests codecs by comparing the real C++ path against a numpy
golden model with shared seeded RNG (tests/test_onebit.py etc.,
tests/utils.py:31-51); same here — byteps_tpu.ops.compression.host IS the
golden model and the server must reproduce it on the aggregate."""

import threading

import numpy as np
import pytest

from byteps_tpu.config import Config
from byteps_tpu.core.registry import TensorRegistry
from byteps_tpu.core.types import DataType
from byteps_tpu.ops.compression import host
from byteps_tpu.server import run_server
from byteps_tpu.server.client import PSClient
from byteps_tpu.server.compressed import CompressedTensor

_PORT = [22800]


def _server(num_workers, **cfgkw):
    port = _PORT[0]
    _PORT[0] += 1
    t = threading.Thread(
        target=run_server,
        args=(port, Config(num_workers=num_workers, num_servers=1, **cfgkw)),
        daemon=True)
    t.start()
    return port, t


def _ctx(name, nbytes, num_workers, partition_bytes=None):
    kw = dict(num_workers=num_workers, num_servers=1)
    if partition_bytes:
        kw["partition_bytes"] = partition_bytes
    reg = TensorRegistry(Config(**kw))
    return reg.init_tensor(name, nbytes, DataType.FLOAT32)


def _two_worker_roundtrip(kwargs, x0, x1, partition_bytes=None):
    num_workers = 2
    port, t = _server(num_workers)
    addr = [f"127.0.0.1:{port}"]
    c0 = PSClient(addr, worker_id=0)
    c1 = PSClient(addr, worker_id=1)
    ct0 = CompressedTensor(c0, _ctx("g", x0.nbytes, 2, partition_bytes),
                           kwargs, 2)
    ct1 = CompressedTensor(c1, _ctx("g", x1.nbytes, 2, partition_bytes),
                           kwargs, 2)
    res = {}

    def w(ct, x, tag):
        res[tag] = ct.push_pull(x, average=False)

    th = threading.Thread(target=w, args=(ct1, x1, "w1"), daemon=True)
    th.start()
    w(ct0, x0, "w0")
    th.join(timeout=30)
    assert not th.is_alive()
    c0.close()
    c1.close(shutdown_servers=False)
    t.join(timeout=10)
    return res["w0"], res["w1"]


def _golden_aggregate(kwargs, xs, n):
    """What the server should produce: decompress each worker's payload,
    sum, recompress (step 0), decompress."""
    payloads = []
    for x in xs:
        c = host.make_host_codec(kwargs, n)
        payloads.append(c.compress(x, step=0))
    dec = host.make_host_codec(kwargs, n)
    s = sum(dec.decompress(np.frombuffer(p, np.uint8)) for p in payloads)
    wire = host.make_host_codec(kwargs, n).compress(s, step=0)
    return dec.decompress(np.frombuffer(wire, np.uint8))


def test_onebit_two_workers():
    n = 1000
    rng = np.random.RandomState(0)
    x0 = rng.randn(n).astype(np.float32)
    x1 = rng.randn(n).astype(np.float32)
    out0, out1 = _two_worker_roundtrip({"compressor": "onebit"}, x0, x1)
    want = _golden_aggregate({"compressor": "onebit"}, [x0, x1], n)
    np.testing.assert_allclose(out0, want, rtol=1e-6)
    np.testing.assert_allclose(out1, want, rtol=1e-6)


def test_topk_two_workers():
    n = 512
    rng = np.random.RandomState(1)
    x0 = rng.randn(n).astype(np.float32)
    x1 = rng.randn(n).astype(np.float32)
    kw = {"compressor": "topk", "k": "32"}
    out0, out1 = _two_worker_roundtrip(kw, x0, x1)
    want = _golden_aggregate(kw, [x0, x1], n)
    np.testing.assert_array_equal(out0, want)
    np.testing.assert_array_equal(out1, want)


def test_randomk_two_workers():
    n = 512
    rng = np.random.RandomState(2)
    x0 = rng.randn(n).astype(np.float32)
    x1 = rng.randn(n).astype(np.float32)
    kw = {"compressor": "randomk", "k": "32", "seed": "7"}
    out0, out1 = _two_worker_roundtrip(kw, x0, x1)
    want = _golden_aggregate(kw, [x0, x1], n)
    np.testing.assert_array_equal(out0, want)
    np.testing.assert_array_equal(out1, want)


def test_dithering_linear_two_workers():
    n = 800
    rng = np.random.RandomState(3)
    x0 = rng.randn(n).astype(np.float32)
    x1 = rng.randn(n).astype(np.float32)
    kw = {"compressor": "dithering", "s": "64", "seed": "11"}
    out0, _ = _two_worker_roundtrip(kw, x0, x1)
    want = _golden_aggregate(kw, [x0, x1], n)
    # linear partition + max norm: all-f32 ops, identical formulas ->
    # bit-exact across numpy and the C++ server
    np.testing.assert_array_equal(out0, want)


def test_dithering_natural_single_worker_mirror():
    """Single worker: the server decompresses exact power-of-two level
    values and requantizes them; that round trip is level-preserving, so
    the output must equal the worker's own decompressed payload — modulo
    rare libm-vs-numpy ulp differences at log2 boundaries."""
    n = 800
    rng = np.random.RandomState(4)
    x0 = rng.randn(n).astype(np.float32)
    kw = {"compressor": "dithering", "s": "64", "seed": "11",
          "partition_type": "natural"}
    port, t = _server(1)
    c = PSClient([f"127.0.0.1:{port}"], worker_id=0)
    ct = CompressedTensor(c, _ctx("g", x0.nbytes, 1), kw, 1)
    out = ct.push_pull(x0, average=False)
    want = _golden_aggregate(kw, [x0], n)
    exact = out == want
    assert exact.mean() >= 0.99, f"only {exact.mean():.3f} exact"
    # any ulp-flip moves one natural level = a factor of 2
    bad = ~exact
    ratio = np.abs(out[bad]) / np.maximum(np.abs(want[bad]), 1e-30)
    assert np.all((ratio > 0.45) & (ratio < 2.2))
    c.close()
    t.join(timeout=10)


def test_partitioned_compressed_roundtrip():
    # tensor large enough to split into multiple partitions; each partition
    # gets its own codec instance and server-side mirror
    n = 8192
    rng = np.random.RandomState(5)
    x0 = rng.randn(n).astype(np.float32)
    x1 = rng.randn(n).astype(np.float32)
    kw = {"compressor": "onebit"}
    out0, _ = _two_worker_roundtrip(kw, x0, x1, partition_bytes=8192)
    # golden per partition (8192 bytes = 2048 f32)
    ctx = _ctx("g", x0.nbytes, 2, partition_bytes=8192)
    assert len(ctx.partitions) > 1
    want = np.empty_like(x0)
    for p in ctx.partitions:
        lo, hi = p.offset // 4, (p.offset + p.length) // 4
        want[lo:hi] = _golden_aggregate(kw, [x0[lo:hi], x1[lo:hi]], hi - lo)
    np.testing.assert_allclose(out0, want, rtol=1e-6)


def test_ef_onebit_unbiases_constant_gradient():
    """Error feedback makes the time-average of compressed gradients
    converge to the true gradient (error_feedback.cc:22-43 semantics)."""
    n = 256
    port, t = _server(1)
    c = PSClient([f"127.0.0.1:{port}"], worker_id=0)
    kw = {"compressor": "onebit", "ef": "vanilla"}
    ct = CompressedTensor(c, _ctx("g", n * 4, 1), kw, 1)
    g = np.linspace(-1.0, 2.0, n).astype(np.float32)
    acc = np.zeros(n, np.float32)
    steps = 250
    for _ in range(steps):
        acc += ct.push_pull(g, average=False)
    mean = acc / steps
    # without EF the onebit mean would be sign(g)*L1mean (one of two
    # constants, max error ~1.0 here); with EF the running mean tracks g
    # with O(scale/steps) bias plus a bounded oscillation
    err = np.abs(mean - g)
    assert err.max() < 0.25, err.max()
    assert err.mean() < 0.05, err.mean()
    c.close()
    t.join(timeout=10)


def test_comp_init_rejected_on_async_server():
    port, t = _server(1, enable_async=True)
    c = PSClient([f"127.0.0.1:{port}"], worker_id=0)
    ctx = _ctx("g", 64 * 4, 1)
    c.init_tensor(ctx, np.zeros(64 * 4, np.uint8).view(np.float32))
    with pytest.raises(RuntimeError, match="comp_init"):
        c.comp_init(0, ctx.partitions[0].key, "compressor=onebit;n=64")
    c.close()
    t.join(timeout=10)


def test_comp_init_requires_initialized_store():
    port, t = _server(1)
    c = PSClient([f"127.0.0.1:{port}"], worker_id=0)
    with pytest.raises(RuntimeError, match="comp_init"):
        c.comp_init(0, 424242, "compressor=onebit;n=64")
    c.close()
    t.join(timeout=10)


def test_dense_push_rejected_on_compressed_key():
    from byteps_tpu.server.compressed import CMD_F32
    port, t = _server(1)
    c = PSClient([f"127.0.0.1:{port}"], worker_id=0)
    ctx = _ctx("g", 64 * 4, 1)
    ct = CompressedTensor(c, ctx, {"compressor": "onebit"}, 1)
    ct.push_pull(np.ones(64, np.float32))
    with pytest.raises(RuntimeError, match="push failed"):
        c.zpush(0, ctx.partitions[0].key, np.zeros(256, np.uint8), CMD_F32)
    c.close()
    t.join(timeout=10)


def test_compressed_ps_training(monkeypatch):
    """End to end: make_ps_train_step(compression=...) trains through the
    compressed wire + server mirror (BASELINE config-4 dataflow)."""
    import jax
    import jax.numpy as jnp
    import optax

    from byteps_tpu.core.state import GlobalState
    from byteps_tpu.jax.train import make_ps_train_step
    from byteps_tpu.models import mlp

    port = _PORT[0]
    _PORT[0] += 1
    monkeypatch.setenv("DMLC_NUM_WORKER", "1")
    monkeypatch.setenv("DMLC_NUM_SERVER", "1")
    monkeypatch.setenv("DMLC_PS_ROOT_URI", "127.0.0.1")
    monkeypatch.setenv("DMLC_PS_ROOT_PORT", str(port))
    monkeypatch.setenv("BYTEPS_FORCE_DISTRIBUTED", "1")
    server = threading.Thread(
        target=run_server,
        args=(port, Config(num_workers=1, num_servers=1)), daemon=True)
    server.start()
    GlobalState._instance = None
    import byteps_tpu as bps
    bps.init()
    try:
        from byteps_tpu.core.state import get_state
        state = get_state()
        cfg = mlp.MLPConfig(in_dim=8, hidden=(16,), n_classes=4)
        params = mlp.init_params(jax.random.PRNGKey(0), cfg)
        tx = optax.sgd(0.1)
        opt = tx.init(params)
        rng = np.random.RandomState(0)
        x = jnp.asarray(rng.randn(32, 8), jnp.float32)
        y = jnp.asarray(rng.randint(0, 4, 32), jnp.int32)
        # device_compress=False pins the HOST-numpy codec tier (the
        # device tier's e2e lives in test_device_compress.py)
        step = make_ps_train_step(
            lambda p, b: mlp.loss_fn(p, b, cfg), tx, state.mesh,
            compression={"compressor": "onebit", "ef": "vanilla"},
            min_compress_bytes=0, device_compress=False)
        losses = []
        for _ in range(25):
            params, opt, loss = step(params, opt, {"x": x, "y": y})
            losses.append(float(loss))
        assert losses[-1] < losses[0] * 0.8, losses
        # elastic: suspend closes the PS client; the step must re-key its
        # compressed registry to the resumed client, not push on the
        # destroyed handle
        bps.suspend()
        bps.resume(num_workers=1, num_servers=1)
        params, opt, loss = step(params, opt, {"x": x, "y": y})
        assert float(loss) < losses[0]
    finally:
        bps.shutdown()
        server.join(timeout=10)
        GlobalState._instance = None


def test_dithering_level_bound_invariant():
    """|level| <= s for every implementation on adversarial inputs (huge
    dynamic range, denormals, single dominant element). The linear-path
    clamp guards the int8 cast at s=127 against any norm that rounds
    below max|x|; no crafted float32 input reliably triggers that rounding
    through np.linalg.norm, so the invariant is pinned property-style
    across host, jnp, and the C++ server instead."""
    import jax.numpy as jnp
    from byteps_tpu.ops.compression.codecs import DitheringCodec

    n = 64
    cases = [
        np.asarray([3.4e38] + [1e-40] * (n - 1), np.float32),
        np.asarray([1.0] * n, np.float32),
        np.concatenate([[7.3], np.full(n - 1, 1e-6)]).astype(np.float32),
    ]
    for norm_t in ("max", "l2"):
        for x in cases:
            h = host.HostDithering(n=n, s=127, normalize=norm_t, seed=1)
            wire = np.frombuffer(h.compress(x, 0), np.uint8)
            lv = wire[:n].view(np.int8)
            assert np.abs(lv.astype(np.int32)).max() <= 127
            assert np.all(np.isfinite(h.decompress(wire)))
            j = DitheringCodec(size=n, s=127, normalize=norm_t, seed=1)
            jlv = np.asarray(j.compress(jnp.asarray(x))["levels"])
            assert np.abs(jlv.astype(np.int32)).max() <= 127

    # server-side: push an all-dominant vector through the C++ mirror
    port, t = _server(1)
    c = PSClient([f"127.0.0.1:{port}"], worker_id=0)
    kw = {"compressor": "dithering", "s": "127", "normalize_type": "l2"}
    ct = CompressedTensor(c, _ctx("g", n * 4, 1), kw, 1)
    out = ct.push_pull(cases[2], average=False)
    assert np.all(np.isfinite(out))
    assert np.sign(out[0]) >= 0
    c.close()
    t.join(timeout=10)


def test_host_matches_jax_codecs():
    """The host wire codecs and the portable jnp codecs must agree — the
    on-device compressor's output is what actually hits the wire."""
    import jax.numpy as jnp
    from byteps_tpu.ops.compression.codecs import (
        DitheringCodec, OnebitCodec, RandomkCodec, TopkCodec,
    )

    n = 300
    x = np.random.RandomState(7).randn(n).astype(np.float32)

    hb = host.HostOnebit(n=n)
    jb = OnebitCodec(size=n, use_pallas=False)
    jp = jb.compress(jnp.asarray(x))
    wire = np.frombuffer(hb.compress(x), np.uint8)
    np.testing.assert_array_equal(wire[:-4].view(np.uint32),
                                  np.asarray(jp["bits"]))
    np.testing.assert_allclose(wire[-4:].view(np.float32)[0],
                               float(jp["scale"]), rtol=1e-6)

    hk = host.HostRandomk(n=n, k=16, seed=3)
    jk = RandomkCodec(size=n, k=16, seed=3)
    np.testing.assert_array_equal(hk.indices(step=5),
                                  np.asarray(jk._indices(5)))

    ht = host.HostTopk(n=n, k=16)
    jt = TopkCodec(size=n, k=16)
    jpk = jt.compress(jnp.asarray(x))
    assert set(np.asarray(jpk["indices"]).tolist()) == \
        set(ht.select(x, 16).tolist())

    hd = host.HostDithering(n=n, s=32, seed=9)
    jd = DitheringCodec(size=n, s=32, seed=9)
    jpd = jd.compress(jnp.asarray(x), step=2)
    hwire = np.frombuffer(hd.compress(x, step=2), np.uint8)
    np.testing.assert_array_equal(hwire[:n].view(np.int8),
                                  np.asarray(jpd["levels"]))


def test_compressed_through_scheduler_pipeline(monkeypatch):
    """Compressed tensors ride the priority-scheduled pipeline (COMPRESS ->
    PUSH -> PULL -> DECOMPRESS stages, the reference's scheduled-queue
    splice, operations.cc:199-204): submit via the async registry path and
    check bit-parity with the blocking path's golden."""
    from byteps_tpu.core.state import GlobalState
    from byteps_tpu.server.compressed import CompressedRegistry

    port = _PORT[0]
    _PORT[0] += 1
    monkeypatch.setenv("DMLC_NUM_WORKER", "1")
    monkeypatch.setenv("DMLC_NUM_SERVER", "1")
    monkeypatch.setenv("DMLC_PS_ROOT_URI", "127.0.0.1")
    monkeypatch.setenv("DMLC_PS_ROOT_PORT", str(port))
    # small credit: partitions are admitted through the credit gate
    monkeypatch.setenv("BYTEPS_SCHEDULING_CREDIT", str(16384))
    server = threading.Thread(
        target=run_server,
        args=(port, Config(num_workers=1, num_servers=1)), daemon=True)
    server.start()
    GlobalState._instance = None
    import byteps_tpu as bps
    bps.init()
    try:
        from byteps_tpu.core.state import get_state
        state = get_state()
        assert state.scheduler is not None
        n = 4096  # multiple partitions at the default 4MB? no — force small
        kw = {"compressor": "onebit"}
        reg = CompressedRegistry(state.ps_client, 1, kw)
        rng = np.random.RandomState(0)
        xs = [rng.randn(n).astype(np.float32) for _ in range(4)]
        handles = [reg.push_pull_async(state, f"cg{i}", x, average=False)
                   for i, x in enumerate(xs)]
        for i, (hd, x) in enumerate(zip(handles, xs)):
            out = bps.synchronize(hd, timeout=60)
            want = _golden_aggregate(kw, [x], n)
            np.testing.assert_allclose(out, want, rtol=1e-6,
                                       err_msg=f"tensor cg{i}")
        # stateful codec across rounds: EF keeps per-partition state and
        # the round counter must advance through the scheduler path too
        kw2 = {"compressor": "randomk", "k": "64", "seed": "5"}
        reg2 = CompressedRegistry(state.ps_client, 1, kw2)
        x = rng.randn(n).astype(np.float32)
        h0 = reg2.push_pull_async(state, "rk", x, average=False)
        out0 = bps.synchronize(h0, timeout=60)
        h1 = reg2.push_pull_async(state, "rk", x, average=False)
        out1 = bps.synchronize(h1, timeout=60)
        # different rounds select different indices -> different outputs
        assert not np.array_equal(out0, out1)
        ct = reg2.get(state, "rk", x)
        assert ct.step == 2
    finally:
        bps.shutdown()
        server.join(timeout=10)
        GlobalState._instance = None


def test_dense_rounds_then_compression_same_key():
    """A key that ran dense rounds and then installs a compressor must
    keep working: the dense ALL_RECV publishes the accumulator by moving
    it out, and the compressed first-recv must re-size it, not memcpy
    into a moved-out buffer (regression: heap corruption)."""
    n = 1024
    port, t = _server(1)
    c = PSClient([f"127.0.0.1:{port}"], worker_id=0)
    ctx = _ctx("g", n * 4, 1)
    rng = np.random.RandomState(8)
    x = rng.randn(n).astype(np.float32)
    # dense rounds first (same keys the compressor will reuse)
    c.init_tensor(ctx, np.zeros(n, np.float32))
    out = c.push_pull(ctx, x.copy(), average=False)
    np.testing.assert_allclose(out, x, rtol=1e-6)
    # now install compression on the SAME key and run compressed rounds
    kw = {"compressor": "onebit"}
    ct = CompressedTensor(c, ctx, kw, 1)
    out = ct.push_pull(x, average=False)
    want = _golden_aggregate(kw, [x], n)
    np.testing.assert_allclose(out, want, rtol=1e-6)
    out2 = ct.push_pull(x, average=False)  # second round exercises steal
    np.testing.assert_allclose(out2, want, rtol=1e-6)
    c.close()
    t.join(timeout=10)


def test_randomk_skewed_steps_degrades_correctly():
    """The server's randomk wire-form fast path requires the round's
    payloads to share indices; workers whose per-tensor round counters
    are skewed (elastic resume) ship DIFFERENT index vectors, and the
    server must fall back to dense aggregation — the aggregate is then
    the sum of each worker's own scatter, exactly like the generic
    path."""
    from byteps_tpu.core.types import RequestType, get_command_type

    n, k = 512, 32
    port, t = _server(2)
    addr = [f"127.0.0.1:{port}"]
    c0 = PSClient(addr, worker_id=0)
    c1 = PSClient(addr, worker_id=1)
    ctx0 = _ctx("skew", n * 4, 2)
    ctx1 = _ctx("skew", n * 4, 2)
    key = ctx0.partitions[0].key
    codec = host.HostRandomk(n=n, k=k, seed=7)
    kw = codec.kwargs_wire()

    def init(c, ctx):
        c.init_tensor(ctx, np.zeros(n, np.float32))
        c.comp_init(0, key, kw)

    ths = [threading.Thread(target=init, args=p)
           for p in ((c0, ctx0), (c1, ctx1))]
    for th in ths:
        th.start()
    for th in ths:
        th.join(60)

    rng = np.random.RandomState(0)
    xs = [rng.randn(n).astype(np.float32) for _ in range(2)]
    steps = [3, 9]  # skewed round counters -> different index vectors
    wires = [codec.compress(xs[i], step=steps[i]) for i in range(2)]
    assert not np.array_equal(codec.indices(3), codec.indices(9))
    cmd = get_command_type(RequestType.COMPRESSED_PUSH_PULL,
                           DataType.FLOAT32)
    outs = [np.empty(n, np.float32) for _ in range(2)]

    def roundtrip(w):
        buf = np.frombuffer(wires[w], np.uint8)
        c = (c0, c1)[w]
        c.zpush(0, key, buf, cmd)
        # pull the DENSE aggregate (not the recompressed wire): the
        # degraded round published the sum of both scatters
        dense_cmd = get_command_type(RequestType.DEFAULT_PUSH_PULL,
                                     DataType.FLOAT32)
        c.zpull(0, key, outs[w], dense_cmd)

    ths = [threading.Thread(target=roundtrip, args=(w,)) for w in range(2)]
    for th in ths:
        th.start()
    for th in ths:
        th.join(60)

    want = codec.decompress(wires[0]) + codec.decompress(wires[1])
    np.testing.assert_allclose(outs[0], want, rtol=1e-6)
    np.testing.assert_array_equal(outs[0], outs[1])
    c0.close()
    c1.close()
    t.join(timeout=15)


def test_varint_codec_roundtrip_property():
    """Vectorized LEB128 helpers: encode->decode is identity across the
    gap-size spectrum (1-byte through 4-byte varints)."""
    from byteps_tpu.ops.compression.host import (
        _varint_decode, _varint_encode,
    )

    rng = np.random.RandomState(0)
    vals = np.concatenate([
        rng.randint(1, 127, 50), rng.randint(128, 1 << 14, 50),
        rng.randint(1 << 14, 1 << 21, 20), rng.randint(1 << 21, 1 << 28, 5),
        [1, 127, 128, 16383, 16384, (1 << 28) - 1],
    ]).astype(np.int64)
    enc = _varint_encode(vals)
    dec, used = _varint_decode(enc, len(vals))
    assert used == len(enc)
    np.testing.assert_array_equal(dec, vals)
    # trailing garbage is not consumed
    dec2, used2 = _varint_decode(np.concatenate([enc, [5, 5]]), len(vals))
    np.testing.assert_array_equal(dec2, vals)
    assert used2 == len(enc)


def test_dithering_varint_wire_bit_exact_and_small():
    """index_coding=varint: decompress(compress(x)) is BIT-EXACT with the
    dense wire's result, and the wire is much smaller than n at low s on
    gradient-like (heavy-tailed) data — the reference's coded sparse
    dithering claim (impl/dithering.cc:25-80)."""
    n = 20000
    rng = np.random.RandomState(0)
    x = (rng.randn(n) ** 3).astype(np.float32)  # heavy tail: most levels 0
    dense = host.HostDithering(n=n, s=7, seed=4)
    sparse = host.HostDithering(n=n, s=7, seed=4, index_coding="varint")
    wd = dense.compress(x, step=3)
    ws = sparse.compress(x, step=3)
    assert len(ws) < n // 4, (len(ws), n)          # wire << n
    assert len(ws) <= sparse.wire_bytes()          # inside the bound
    np.testing.assert_array_equal(sparse.decompress(np.frombuffer(ws, np.uint8)),
                                  dense.decompress(np.frombuffer(wd, np.uint8)))
    # dense data (low sparsity) still round-trips, just without the win
    xd = rng.randn(256).astype(np.float32)
    s2 = host.HostDithering(n=256, s=127, seed=1, index_coding="varint")
    d2 = host.HostDithering(n=256, s=127, seed=1)
    np.testing.assert_array_equal(
        s2.decompress(np.frombuffer(s2.compress(xd, 0), np.uint8)),
        d2.decompress(np.frombuffer(d2.compress(xd, 0), np.uint8)))


def test_dithering_varint_two_workers():
    """The C++ server speaks the varint wire: decompress, sum, recompress
    (variable-length reply) — aggregate matches the numpy golden."""
    n = 4000
    rng = np.random.RandomState(6)
    x0 = (rng.randn(n) ** 3).astype(np.float32)
    x1 = (rng.randn(n) ** 3).astype(np.float32)
    kw = {"compressor": "dithering", "s": "7", "seed": "11",
          "index_coding": "varint"}
    out0, out1 = _two_worker_roundtrip(kw, x0, x1)
    want = _golden_aggregate(kw, [x0, x1], n)
    np.testing.assert_array_equal(out0, want)
    np.testing.assert_array_equal(out1, want)


def test_dithering_varint_through_scheduler(monkeypatch):
    """Variable-length replies ride the pipelined scheduler path (the
    PULL stage must use the actual reply length, not the bound)."""
    from byteps_tpu.core.state import GlobalState
    from byteps_tpu.server.compressed import CompressedRegistry

    port = _PORT[0]
    _PORT[0] += 1
    monkeypatch.setenv("DMLC_NUM_WORKER", "1")
    monkeypatch.setenv("DMLC_NUM_SERVER", "1")
    monkeypatch.setenv("DMLC_PS_ROOT_URI", "127.0.0.1")
    monkeypatch.setenv("DMLC_PS_ROOT_PORT", str(port))
    server = threading.Thread(
        target=run_server,
        args=(port, Config(num_workers=1, num_servers=1)), daemon=True)
    server.start()
    GlobalState._instance = None
    import byteps_tpu as bps
    bps.init()
    try:
        from byteps_tpu.core.state import get_state
        state = get_state()
        n = 4096
        kw = {"compressor": "dithering", "s": "7", "seed": "2",
              "index_coding": "varint"}
        reg = CompressedRegistry(state.ps_client, 1, kw)
        rng = np.random.RandomState(1)
        x = (rng.randn(n) ** 3).astype(np.float32)
        hd = reg.push_pull_async(state, "vd", x, average=False)
        out = bps.synchronize(hd, timeout=60)
        want = _golden_aggregate(kw, [x], n)
        np.testing.assert_array_equal(out, want)
    finally:
        bps.shutdown()
        server.join(timeout=10)
        GlobalState._instance = None
