"""DCN parameter-server tests: all roles on localhost over loopback TCP —
the reference's MetaTest pattern (tests/meta_test.py:27-86), with servers on
background threads instead of subprocesses (the native Run loop releases
the GIL).

Covers: init-push barrier, sync aggregation (first-copy/sum/all-recv),
parked pulls, multi-server key sharding via the registry, async mode,
barrier, multi-round training-loop shape, and elastic reconnect.
"""

import threading
import time

import numpy as np
import pytest

from byteps_tpu.config import Config
from byteps_tpu.core.registry import TensorRegistry
from byteps_tpu.core.types import DataType, RequestType, get_command_type
from byteps_tpu.server import run_server
from byteps_tpu.server.client import PSClient

_NEXT_PORT = [19350]


def start_servers(n_servers: int, num_workers: int, async_mode: bool = False,
                  schedule: bool = False):
    """Spawn n servers on fresh loopback ports; returns (addrs, threads)."""
    import os
    base = _NEXT_PORT[0]
    _NEXT_PORT[0] += n_servers
    cfgkw = dict(num_workers=num_workers, enable_async=async_mode,
                 server_enable_schedule=schedule, num_servers=n_servers)
    threads = []
    for i in range(n_servers):
        cfg = Config(**cfgkw)
        t = threading.Thread(target=run_server, args=(base + i, cfg),
                             daemon=True)
        t.start()
        threads.append(t)
    addrs = [f"127.0.0.1:{base + i}" for i in range(n_servers)]
    return addrs, threads


CMD_F32 = get_command_type(RequestType.DEFAULT_PUSH_PULL, DataType.FLOAT32)


def test_single_worker_roundtrip():
    addrs, threads = start_servers(1, num_workers=1)
    c = PSClient(addrs, worker_id=0)
    x = np.arange(100, dtype=np.float32)
    c.init_key(0, 7, np.zeros_like(x), CMD_F32)
    c.zpush(0, 7, x, CMD_F32)
    out = np.empty_like(x)
    c.zpull(0, 7, out, CMD_F32)
    np.testing.assert_array_equal(out, x)
    c.close()
    for t in threads:
        t.join(timeout=10)
        assert not t.is_alive()


def test_server_throttle_caps_bandwidth(monkeypatch):
    """BYTEPS_SERVER_THROTTLE_MBPS (the scaling-rule evidence knob,
    docs/best-practice.md) pins the server's payload rate to the cap:
    a 4MB round trip through a 20MB/s server must take ~0.4s/round
    (2x4MB through one bucket), where the unthrottled loopback moves
    GB/s. Asserts both sides: slower than half the wire would allow
    unthrottled, and not pathologically slower than the cap predicts."""
    # NOTE: the env must stay set until the server thread CONSTRUCTS the
    # native Server (the Throttle ctor reads it); monkeypatch restores
    # it at test end, after the server is long up
    monkeypatch.setenv("BYTEPS_SERVER_THROTTLE_MBPS", "20")
    addrs, threads = start_servers(1, num_workers=1)
    c = PSClient(addrs, worker_id=0)
    x = np.random.RandomState(0).randn(1 << 20).astype(np.float32)  # 4MB
    c.init_key(0, 7, np.zeros_like(x), CMD_F32)
    out = np.empty_like(x)
    c.zpush(0, 7, x, CMD_F32)
    c.zpull(0, 7, out, CMD_F32)  # warmup: drains the 50ms burst credit
    t0 = time.perf_counter()
    rounds = 2
    for _ in range(rounds):
        c.zpush(0, 7, x, CMD_F32)
        c.zpull(0, 7, out, CMD_F32)
    dt = time.perf_counter() - t0
    np.testing.assert_allclose(out, x, rtol=1e-5)
    expected = rounds * 2 * x.nbytes / 20e6  # ~0.84s
    assert dt > expected * 0.5, f"throttle not binding: {dt:.3f}s"
    assert dt < expected * 3.0, f"throttle overshooting: {dt:.3f}s"
    c.close()
    for t in threads:
        t.join(timeout=10)
        assert not t.is_alive()


def test_throttled_servers_scale_bandwidth(monkeypatch):
    """The scaling-rule evidence pair (docs/best-practice.md): with the
    server made the bottleneck by construction (throttle sleeps its
    threads), splitting the key space over TWO equally-throttled
    servers must take materially LESS wall time than one — the
    min(server bw, worker bw) doubling, core-independent. Generous
    bounds: the 2srv wall must be under 0.75x the 1srv wall (ideal
    0.5x), and the 1srv wall must be within its cap's predicted range.

    Each configuration times BEST-OF-2 rounds (bench.py's _best_of
    rationale): on a loaded shared host, scheduler jitter hitting the
    two wall() calls asymmetrically can push a single draw past the
    0.75x bound — the per-rep spread here has measured >50%; the best
    round is the capability number the rule speaks about."""
    monkeypatch.setenv("BYTEPS_SERVER_THROTTLE_MBPS", "25")
    x = [np.random.RandomState(i).randn(1 << 19).astype(np.float32)
         for i in range(8)]  # 8 x 2MB keys, placed explicitly below

    def wall(n_servers: int) -> float:
        addrs, threads = start_servers(n_servers, num_workers=1)
        c = PSClient(addrs, worker_id=0)
        srv = [i % n_servers for i in range(len(x))]  # even key split
        for i, g in enumerate(x):
            c.init_key(srv[i], 7 + i, np.zeros_like(g), CMD_F32)

        def one_round():
            # two client threads, keys split between them (the pipeline
            # scheduler's shape): with 2 servers each thread's keys live
            # on its own server, so the two token buckets drain in
            # parallel; with 1 server both threads share one bucket —
            # which is exactly the rule under test. Futures, not bare
            # threads: a zpush/zpull error must FAIL the test, not
            # silently shorten the timed round (same hazard the
            # two-client test below documents)
            import concurrent.futures

            def drain(tid):
                out = np.empty_like(x[0])
                for i, g in enumerate(x):
                    if i % 2 != tid:
                        continue
                    c.zpush(srv[i], 7 + i, g, CMD_F32)
                    c.zpull(srv[i], 7 + i, out, CMD_F32)

            with concurrent.futures.ThreadPoolExecutor(2) as ex:
                for f in [ex.submit(drain, t) for t in range(2)]:
                    f.result(timeout=60)

        one_round()  # warmup: drains burst credit, init barrier
        dt = float("inf")
        for _ in range(2):  # best-of-2: see docstring
            t0 = time.perf_counter()
            one_round()
            dt = min(dt, time.perf_counter() - t0)
        c.close()
        for t in threads:
            t.join(timeout=10)
        return dt

    one = wall(1)
    two = wall(2)
    # 16MB payload x 2 dirs / 25MB/s = ~1.28s expected for 1 server:
    # bounded BOTH ways so an overshooting throttle (which would also
    # inflate `one` and trivially satisfy the ratio) fails loudly
    expected = sum(g.nbytes for g in x) * 2 / 25e6
    assert one > expected * 0.4, f"throttle not binding: {one:.3f}s"
    assert one < expected * 3.0, f"throttle overshooting: {one:.3f}s"
    assert two < one * 0.75, (f"2 throttled servers did not scale: "
                              f"1srv {one:.3f}s vs 2srv {two:.3f}s")


def test_two_workers_sum_and_parked_pull():
    addrs, threads = start_servers(1, num_workers=2)
    c0 = PSClient(addrs, worker_id=0)
    c1 = PSClient(addrs, worker_id=1)
    x0 = np.full(64, 1.5, np.float32)
    x1 = np.full(64, 2.0, np.float32)

    t_init = threading.Thread(
        target=lambda: c1.init_key(0, 3, np.zeros_like(x1), CMD_F32))
    t_init.start()
    c0.init_key(0, 3, np.zeros_like(x0), CMD_F32)  # blocks till both arrive
    t_init.join(timeout=10)
    assert not t_init.is_alive()

    # worker 0 pushes and pulls immediately: the pull must PARK until
    # worker 1's push completes the round
    out0 = np.empty_like(x0)
    done0 = threading.Event()

    def w0():
        c0.zpush(0, 3, x0, CMD_F32)
        c0.zpull(0, 3, out0, CMD_F32)
        done0.set()

    th = threading.Thread(target=w0)
    th.start()
    time.sleep(0.3)
    assert not done0.is_set()          # parked: round incomplete
    c1.zpush(0, 3, x1, CMD_F32)        # completes the round
    assert done0.wait(timeout=10)
    np.testing.assert_allclose(out0, x0 + x1)
    out1 = np.empty_like(x1)
    c1.zpull(0, 3, out1, CMD_F32)
    np.testing.assert_allclose(out1, x0 + x1)
    c0.close()
    c1.close()


@pytest.mark.parametrize("dtype_name", ["float16", "bfloat16", "uint16"])
def test_two_workers_16bit_sum(dtype_name):
    """fp16/bf16/u16 summation on the server: the second worker's push hits
    sum_into (the first is a COPY_FIRST memcpy), which the reference handles
    with an AVX F16C convert-add-convert path (cpu_reducer.cc:59-120). Sums
    must match numpy's same-dtype arithmetic bit-for-bit (both do f32
    accumulate + round-to-nearest-even per element)."""
    import ml_dtypes

    if dtype_name == "float16":
        npdt, wire_dt = np.float16, DataType.FLOAT16
    elif dtype_name == "bfloat16":
        npdt, wire_dt = ml_dtypes.bfloat16, DataType.BFLOAT16
    else:
        npdt, wire_dt = np.uint16, DataType.UINT16
    cmd = get_command_type(RequestType.DEFAULT_PUSH_PULL, wire_dt)

    addrs, threads = start_servers(1, num_workers=2)
    c0 = PSClient(addrs, worker_id=0)
    c1 = PSClient(addrs, worker_id=1)
    rng = np.random.RandomState(7)
    if dtype_name == "uint16":
        x0 = rng.randint(0, 30000, 512).astype(np.uint16)
        x1 = rng.randint(0, 30000, 512).astype(np.uint16)
        expect = (x0 + x1).view(np.uint16)
    else:
        # include subnormals, large values, and exact-halfway cases
        x0 = (rng.randn(512) * 100).astype(npdt)
        x1 = (rng.randn(512) * 100).astype(npdt)
        x0[:4] = [npdt(6e-8), npdt(-6e-8), npdt(0), npdt(65000.0 if
                  dtype_name == "float16" else 3e38)]
        x1[:4] = [npdt(6e-8), npdt(6e-8), npdt(-0.0), npdt(65000.0 if
                  dtype_name == "float16" else 3e38)]
        # expectation mirrors the server's arithmetic (f32 accumulate,
        # then round to the wire dtype); errstate silences the DESIGNED
        # overflow of lane 3 (65000+65000 > f16 max -> inf on both sides)
        with np.errstate(over="ignore"):
            expect = (x0.astype(np.float32)
                      + x1.astype(np.float32)).astype(npdt)
        # prove the comparison isn't inf==inf throughout: exactly the
        # overflow lane is inf, every other lane is finite
        as_f32 = expect.astype(np.float32)
        assert not np.isfinite(as_f32[3])
        assert np.isfinite(np.delete(as_f32, 3)).all()

    wire0 = x0.view(np.uint16)
    wire1 = x1.view(np.uint16)
    t = threading.Thread(
        target=lambda: c1.init_key(0, 5, np.zeros(512, np.uint16), cmd))
    t.start()
    c0.init_key(0, 5, np.zeros(512, np.uint16), cmd)
    t.join(timeout=10)

    t = threading.Thread(target=lambda: c1.zpush(0, 5, wire1, cmd))
    t.start()
    c0.zpush(0, 5, wire0, cmd)
    t.join(timeout=10)
    out = np.empty(512, np.uint16)
    c0.zpull(0, 5, out, cmd)
    np.testing.assert_array_equal(out, expect.view(np.uint16))
    c0.close()
    c1.close()
    for th in threads:
        th.join(timeout=10)
        assert not th.is_alive()


def test_unknown_dtype_rejected_at_init():
    """An out-of-enum wire dtype must be error-replied at init (before a
    store exists) — otherwise a later steady-state push would no-op in
    sum_into and silently publish un-summed data."""
    addrs, threads = start_servers(1, num_workers=1)
    c = PSClient(addrs, worker_id=0)
    bad_cmd = get_command_type(RequestType.DEFAULT_PUSH_PULL, 99)
    with pytest.raises(RuntimeError):
        c.init_key(0, 11, np.zeros(16, np.float32), bad_cmd)
    # the server survives and still serves valid traffic
    c.init_key(0, 12, np.zeros(16, np.float32), CMD_F32)
    c.zpush(0, 12, np.ones(16, np.float32), CMD_F32)
    out = np.empty(16, np.float32)
    c.zpull(0, 12, out, CMD_F32)
    np.testing.assert_allclose(out, 1.0)
    c.close()


def test_multi_server_partitioned_tensor():
    """A 100KB tensor partitioned into 4KB keys spread across 3 servers
    through the registry's hashing, push_pulled at the tensor level."""
    addrs, threads = start_servers(3, num_workers=1)
    reg = TensorRegistry(Config(num_servers=3, partition_bytes=4096))
    ctx = reg.init_tensor("grad/w", nbytes=100_000, dtype=DataType.FLOAT32)
    assert len(ctx.partitions) == 25
    assert len({p.server for p in ctx.partitions}) > 1  # actually spread

    c = PSClient(addrs, worker_id=0)
    x = np.random.RandomState(0).randn(25_000).astype(np.float32)
    c.init_tensor(ctx, np.zeros_like(x))
    out = c.push_pull(ctx, x, average=False)
    np.testing.assert_array_equal(out, x)
    # second round (steady state reuses stores)
    out2 = c.push_pull(ctx, x * 2, average=False)
    np.testing.assert_array_equal(out2, x * 2)
    c.close()


def test_async_mode_accumulates():
    addrs, threads = start_servers(1, num_workers=1, async_mode=True)
    c = PSClient(addrs, worker_id=0)
    x = np.ones(32, np.float32)
    c.init_key(0, 1, np.zeros_like(x), CMD_F32)
    out = np.empty_like(x)
    # async: every push adds into the authoritative store; pulls answer
    # immediately (server.cc:315-319,380-382)
    c.zpush(0, 1, x, CMD_F32)
    c.zpull(0, 1, out, CMD_F32)
    np.testing.assert_allclose(out, 1.0)
    c.zpush(0, 1, x, CMD_F32)
    c.zpull(0, 1, out, CMD_F32)
    np.testing.assert_allclose(out, 2.0)
    c.close()


def test_barrier_releases_all_workers():
    addrs, threads = start_servers(1, num_workers=2)
    c0 = PSClient(addrs, worker_id=0)
    c1 = PSClient(addrs, worker_id=1)
    reached = []

    def wait(c, i):
        c.barrier()
        reached.append(i)

    t0 = threading.Thread(target=wait, args=(c0, 0))
    t0.start()
    time.sleep(0.3)
    assert reached == []               # barrier holds until all arrive
    wait(c1, 1)
    t0.join(timeout=10)
    assert sorted(reached) == [0, 1]
    c0.close()
    c1.close()


def test_training_loop_shape_two_workers():
    """Simulated 2-worker data-parallel loop: each round both workers push
    local grads, pull the sum, apply the same update — weights stay
    identical (the consistency the reference's whole pipeline exists to
    provide)."""
    addrs, threads = start_servers(2, num_workers=2)
    reg = TensorRegistry(Config(num_servers=2, partition_bytes=4096))
    ctx = reg.init_tensor("w", nbytes=40_000, dtype=DataType.FLOAT32)
    c0 = PSClient(addrs, worker_id=0)
    c1 = PSClient(addrs, worker_id=1)
    w0 = np.zeros(10_000, np.float32)
    w1 = np.zeros(10_000, np.float32)
    # JOIN the init barrier via futures (a fixed sleep raced it on
    # loaded hosts, and a bare Thread swallowed exceptions — join()
    # does not re-raise; future.result() does): both inits return only
    # after every worker's init push arrived
    import concurrent.futures

    with concurrent.futures.ThreadPoolExecutor(2) as pool:
        futs = [pool.submit(c.init_tensor, ctx, np.zeros_like(w0))
                for c in (c0, c1)]
        for f in futs:
            f.result(timeout=30)

    rng = np.random.RandomState(0)
    for step in range(3):
        g0 = rng.randn(10_000).astype(np.float32)
        g1 = rng.randn(10_000).astype(np.float32)
        res = {}

        def worker(c, g, tag):
            res[tag] = c.push_pull(ctx, g, average=True, num_workers=2)

        ta = threading.Thread(target=worker, args=(c0, g0, "a"))
        tb = threading.Thread(target=worker, args=(c1, g1, "b"))
        ta.start(); tb.start(); ta.join(10); tb.join(10)
        expected = (g0 + g1) / 2
        np.testing.assert_allclose(res["a"], expected, rtol=1e-6)
        np.testing.assert_allclose(res["b"], expected, rtol=1e-6)
        w0 -= 0.1 * res["a"]
        w1 -= 0.1 * res["b"]
    np.testing.assert_array_equal(w0, w1)
    c0.close()
    c1.close()


def test_elastic_reconnect():
    """Suspend-style disconnect (servers stay up) then reconnect and keep
    using the same keys (global.cc:431-436 resume semantics)."""
    addrs, threads = start_servers(1, num_workers=1)
    c = PSClient(addrs, worker_id=0)
    x = np.ones(16, np.float32)
    c.init_key(0, 5, np.zeros_like(x), CMD_F32)
    c.zpush(0, 5, x, CMD_F32)
    out = np.empty_like(x)
    c.zpull(0, 5, out, CMD_F32)
    c.close(shutdown_servers=False)    # suspend: servers keep running

    c2 = PSClient(addrs, worker_id=0)  # resume
    c2.zpush(0, 5, x * 3, CMD_F32)
    out2 = np.empty_like(x)
    c2.zpull(0, 5, out2, CMD_F32)
    np.testing.assert_allclose(out2, 3.0)
    c2.close()


def test_async_push_roundtrip_and_reject():
    """zpush_async: (a) the happy path round-trips like zpush (the pull
    is the synchronization — per-key FIFO via key-affine conns); (b) a
    server-rejected async push poisons the connection so the paired pull
    fails PROMPTLY (bounded seconds), not after the 600s client timeout:
    the server never counted the push, so the round could otherwise
    never complete."""
    # (the 600s default client timeout is latched process-wide on first
    # request — the <30s assertion below is what proves fail-fast)
    addrs, threads = start_servers(1, num_workers=1)
    c = PSClient(addrs, worker_id=0)
    x = np.arange(256, dtype=np.float32)
    c.init_key(0, 9, np.zeros_like(x), CMD_F32)
    c.zpush_async(0, 9, x, CMD_F32)
    out = np.empty_like(x)
    c.zpull(0, 9, out, CMD_F32)
    np.testing.assert_array_equal(out, x)

    # rejected push: a steady-state PUSH with a length that does not
    # match the store is error-ACKed by the server
    bad = np.zeros(7, np.float32)
    c.zpush_async(0, 9, bad, CMD_F32)
    t0 = time.time()
    with pytest.raises(RuntimeError):
        c.zpull(0, 9, out, CMD_F32)
    assert time.time() - t0 < 30, "poisoned conn did not fail fast"
    c.close()
    for t in threads:
        t.join(timeout=10)
        assert not t.is_alive()
