"""Fault-tolerant elastic PS fleet (docs/fault-tolerance.md): bounded
wire retry with exponential backoff, (round, attempt)-epoch idempotent
replay, live key migration off a dead server, and the BYTEPS_CHAOS_*
fault-injection knobs.

The protocol-level pieces (replay dedup, registry migration, the retry
engine) test in-process; anything that depends on BYTEPS_CLIENT_TIMEOUT_S
runs in a SUBPROCESS (the native timeout is latched per process at first
use, so an in-process test would inherit whatever an earlier test
latched); the churn test SIGKILLs a real server subprocess mid-training.
"""

import os
import signal
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from byteps_tpu.config import Config
from byteps_tpu.core.registry import TensorRegistry
from byteps_tpu.core.types import DataType, RequestType, get_command_type
from byteps_tpu.server import run_server
from byteps_tpu.server.client import PSClient

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_PORT = [27300]

CMD_F32 = get_command_type(RequestType.DEFAULT_PUSH_PULL, DataType.FLOAT32)


def _epoch(round_no: int, attempt: int = 0) -> int:
    return (round_no << 16) | attempt


def _server_thread(num_workers=1):
    port = _PORT[0]
    _PORT[0] += 1
    t = threading.Thread(
        target=run_server,
        args=(port, Config(num_workers=num_workers, num_servers=1)),
        daemon=True)
    t.start()
    return port, t


def _spawn_server_proc(port, num_workers=1, num_servers=1, extra_env=None):
    """A REAL server process (SIGKILL-able, chaos-knob-able)."""
    code = (f"from byteps_tpu.server import run_server; "
            f"from byteps_tpu.config import Config; "
            f"run_server({port}, Config(num_workers={num_workers}, "
            f"num_servers={num_servers}))")
    env = {**os.environ,
           "PYTHONPATH": REPO + os.pathsep + os.environ.get(
               "PYTHONPATH", ""),
           **(extra_env or {})}
    return subprocess.Popen([sys.executable, "-c", code], env=env)


def _wait_ports(ports, timeout=60):
    """Block until every port accepts connections: the server processes
    pay a cold jax import before they bind, which can outlast the native
    client's own 10s connect-retry window."""
    from byteps_tpu.utils.net import wait_port

    deadline = time.monotonic() + timeout
    for port in ports:
        wait_port(port, max(1.0, deadline - time.monotonic()))


# --------------------------------------------------------------------- #
# idempotent replay: the (round, attempt) epoch dedup
# --------------------------------------------------------------------- #


@pytest.mark.chaos
def test_replayed_push_never_double_counts():
    """THE double-count scenario the epoch stamp exists for: worker 0's
    round-1 push is replayed (its reply was lost); without dedup the
    duplicate would be folded as worker 1's contribution and the round
    would publish 2*w0 — with it, the aggregate is exactly w0 + w1."""
    port, t = _server_thread(num_workers=2)
    addr = [f"127.0.0.1:{port}"]
    c0 = PSClient(addr, worker_id=0)
    c1 = PSClient(addr, worker_id=1)
    n = 512
    x0 = np.arange(n, dtype=np.float32)
    x1 = np.full(n, 10.0, np.float32)
    key = 3

    th = threading.Thread(
        target=c0.init_key, args=(0, key, np.zeros(n, np.float32), CMD_F32),
        daemon=True)
    th.start()
    c1.init_key(0, key, np.zeros(n, np.float32), CMD_F32)  # init barrier
    th.join(timeout=15)
    assert not th.is_alive()

    c0.zpush(0, key, x0, CMD_F32, epoch=_epoch(1))
    c0.zpush(0, key, x0, CMD_F32, epoch=_epoch(1, attempt=1))  # replay
    time.sleep(0.3)  # both w0 pushes are folded (or deduped) server-side
    c1.zpush(0, key, x1, CMD_F32, epoch=_epoch(1))

    out0 = np.empty(n, np.float32)
    out1 = np.empty(n, np.float32)
    c0.zpull(0, key, out0, CMD_F32, exact=True)
    c1.zpull(0, key, out1, CMD_F32, exact=True)
    np.testing.assert_array_equal(out0, x0 + x1)  # NOT 2*x0 (no w1 fold)
    np.testing.assert_array_equal(out1, x0 + x1)

    # a NEW round folds normally (dedup compares rounds, not presence)
    c0.zpush(0, key, x0 * 2, CMD_F32, epoch=_epoch(2))
    c1.zpush(0, key, x1 * 2, CMD_F32, epoch=_epoch(2))
    c0.zpull(0, key, out0, CMD_F32, exact=True)
    np.testing.assert_array_equal(out0, 2 * (x0 + x1))

    # BOTH workers SHUTDOWN: a 2-worker server counts shutdowns against
    # num_workers — one would leave a live server thread leaked into
    # the rest of the suite (and a 10s join timeout here)
    c0.close()
    c1.close()
    t.join(timeout=10)


@pytest.mark.chaos
def test_unstamped_push_keeps_legacy_semantics():
    """epoch=0 (legacy callers / blocking client) must keep positional
    counting: for one worker each unstamped push is its own round."""
    port, t = _server_thread(num_workers=1)
    c = PSClient([f"127.0.0.1:{port}"], worker_id=0)
    n = 64
    x = np.ones(n, np.float32)
    c.init_key(0, 5, np.zeros(n, np.float32), CMD_F32)
    c.zpush(0, 5, x, CMD_F32)          # round 1 (unstamped)
    c.zpush(0, 5, x * 3, CMD_F32)      # round 2 (unstamped)
    out = np.empty(n, np.float32)
    c.zpull(0, 5, out, CMD_F32, exact=True)
    np.testing.assert_array_equal(out, x * 3)  # latest round's aggregate
    c.close()
    t.join(timeout=10)


# --------------------------------------------------------------------- #
# registry: live key migration
# --------------------------------------------------------------------- #


def _registry(num_servers, partition_bytes=4096):
    return TensorRegistry(Config(num_workers=1, num_servers=num_servers,
                                 partition_bytes=partition_bytes))


def test_migrate_server_retargets_and_rebalances():
    reg = _registry(3)
    for i in range(6):
        reg.init_tensor(f"m{i}", 3 * 4096, DataType.FLOAT32)  # 3 parts
    before = reg.server_loads()
    assert sum(before) == 6 * 3 * 4096
    v0 = reg.routing_version
    migrated = reg.migrate_server(1)
    assert migrated, "server 1 owned nothing — partitioning changed?"
    assert reg.routing_version == v0 + 1
    assert reg.dead_servers() == [1]
    loads = reg.server_loads()
    assert loads[1] == 0
    assert sum(loads) == sum(before)  # bytes conserved, just re-homed
    for ctx in reg.contexts_in_order():
        for p in ctx.partitions:
            assert p.server != 1
    # NEW declarations avoid the dead server too
    ctx = reg.init_tensor("post_death", 8 * 4096, DataType.FLOAT32)
    assert all(p.server != 1 for p in ctx.partitions)
    # idempotent: a second migrate of the same server moves nothing
    assert reg.migrate_server(1) == []


def test_migrate_server_is_deterministic_across_workers():
    """Two independent registries with the same declaration history must
    migrate every key to the same survivor — workers observe a death
    independently and may never diverge on routing."""
    regs = [_registry(4) for _ in range(2)]
    for reg in regs:
        for i in range(5):
            reg.init_tensor(f"d{i}", 2 * 4096, DataType.FLOAT32)
    for reg in regs:
        reg.migrate_server(2)
    tables = []
    for reg in regs:
        tables.append([(p.key, p.server)
                       for ctx in reg.contexts_in_order()
                       for p in ctx.partitions])
    assert tables[0] == tables[1]


def test_migrate_last_survivor_raises():
    reg = _registry(2)
    reg.init_tensor("x", 4096, DataType.FLOAT32)
    reg.migrate_server(0)
    with pytest.raises(RuntimeError, match="no surviving server"):
        reg.migrate_server(1)


# --------------------------------------------------------------------- #
# scheduler retry engine (fake client: deterministic, no network)
# --------------------------------------------------------------------- #


class _FlakyClient:
    """supports_fused client whose wire fails the first ``fail_n`` sends
    (send-time exception), then succeeds by echoing the payload."""

    supports_fused = True

    def __init__(self, fail_n):
        self.fail_n = fail_n
        self.calls = 0

    def ensure_init(self, ctx, nbytes):
        pass

    def zpushpull_async(self, server, key, data, out, cmd, on_done,
                        epoch=0):
        self.calls += 1
        if self.calls <= self.fail_n:
            raise RuntimeError("injected wire failure")
        out[:] = np.asarray(data).view(np.uint8)
        on_done(len(out), None)


def _mk_ctx(name="t", nbytes=256):
    reg = _registry(1, partition_bytes=1 << 20)
    return reg.init_tensor(name, nbytes, DataType.FLOAT32)


def test_scheduler_retries_then_succeeds(monkeypatch):
    from byteps_tpu.core.scheduler import Handle, PipelineScheduler

    monkeypatch.setenv("BYTEPS_WIRE_RETRY", "3")
    monkeypatch.setenv("BYTEPS_WIRE_BACKOFF_MS", "5")
    client = _FlakyClient(fail_n=2)
    sched = PipelineScheduler(client)
    try:
        ctx = _mk_ctx()
        x = np.arange(64, dtype=np.float32)
        h = Handle(0, "t")
        sched.submit(ctx, x, h, average=False, num_workers=1)
        out = h.wait(timeout=20)
        np.testing.assert_array_equal(out, x)
        assert client.calls == 3  # 2 failures + 1 success
    finally:
        sched.stop()


def test_scheduler_retry_budget_fails_fast_with_clear_error(monkeypatch):
    from byteps_tpu.core.scheduler import Handle, PipelineScheduler

    monkeypatch.setenv("BYTEPS_WIRE_RETRY", "2")
    monkeypatch.setenv("BYTEPS_WIRE_BACKOFF_MS", "5")
    client = _FlakyClient(fail_n=10**9)  # permanently failing wire
    sched = PipelineScheduler(client)
    try:
        ctx = _mk_ctx("dead")
        h = Handle(0, "dead")
        t0 = time.monotonic()
        sched.submit(ctx, np.ones(64, np.float32), h, average=False,
                     num_workers=1)
        with pytest.raises(RuntimeError, match="after 3 attempts"):
            h.wait(timeout=30)
        assert time.monotonic() - t0 < 10, "retry budget not bounded"
        assert client.calls == 3
    finally:
        sched.stop()


def test_scheduler_programming_errors_do_not_retry(monkeypatch):
    from byteps_tpu.core.scheduler import Handle, PipelineScheduler

    monkeypatch.setenv("BYTEPS_WIRE_RETRY", "5")

    class _BadClient(_FlakyClient):
        def zpushpull_async(self, *a, **kw):
            self.calls += 1
            raise ValueError("caller bug")

    client = _BadClient(fail_n=0)
    sched = PipelineScheduler(client)
    try:
        ctx = _mk_ctx("bug")
        h = Handle(0, "bug")
        sched.submit(ctx, np.ones(8, np.float32), h, average=False,
                     num_workers=1)
        with pytest.raises(ValueError, match="caller bug"):
            h.wait(timeout=20)
        assert client.calls == 1  # no retry burned on a ValueError
    finally:
        sched.stop()


# --------------------------------------------------------------------- #
# chaos drop-reply idempotence (subprocess: the native client timeout is
# latched per process, and the drop knob is read per server instance)
# --------------------------------------------------------------------- #

_DROP_SCRIPT = r"""
import os, sys, threading
sys.path.insert(0, os.environ["BPS_REPO"])
import numpy as np
from byteps_tpu.config import Config
from byteps_tpu.core.state import GlobalState
from byteps_tpu.server import run_server
from byteps_tpu.utils.net import free_port

port = free_port()
os.environ.update({
    "DMLC_NUM_WORKER": "1", "DMLC_NUM_SERVER": "1",
    "DMLC_PS_ROOT_URI": "127.0.0.1", "DMLC_PS_ROOT_PORT": str(port),
    "BYTEPS_FORCE_DISTRIBUTED": "1",
})
# the server instance reads the drop knob at construction
server = threading.Thread(
    target=run_server, args=(port, Config(num_workers=1, num_servers=1)),
    daemon=True)
server.start()
GlobalState._instance = None
import byteps_tpu as bps
bps.init()
rng = np.random.RandomState(3)
grads = [rng.randn(1024).astype(np.float32) for _ in range(4)]
for r in range(4):
    hs = [bps.push_pull_async(g * (r + 1), f"g{i}", average=False)
          for i, g in enumerate(grads)]
    for h, g in zip(hs, grads):
        out = bps.synchronize(h, timeout=60)
        # 1 worker: the aggregate IS the pushed tensor — bitwise, even
        # though replies were dropped and pushes replayed along the way
        assert np.array_equal(out, g * (r + 1)), (r, "double-counted?")
snap = bps.get_metrics()
retries = int(snap["counters"].get("wire/retries", 0))
assert retries > 0, "chaos produced no retries - knob dead?"
assert int(snap["counters"].get("wire/server_failovers", 0)) == 0
bps.shutdown()
server.join(timeout=15)
print("DROP_OK retries=", retries)
"""


@pytest.mark.chaos
def test_dropped_replies_retry_bitwise_identical():
    """Forced reply drops + epoch-stamped retries produce bitwise-exact
    aggregates (the acceptance idempotence proof, test-side twin of
    ``bench.py --phase churn_ab``)."""
    env = {**os.environ,
           "BPS_REPO": REPO,
           "BYTEPS_CLIENT_TIMEOUT_S": "2",
           "BYTEPS_WIRE_RETRY": "5",
           "BYTEPS_WIRE_BACKOFF_MS": "25",
           "BYTEPS_CHAOS_DROP_REPLY_RATE": "0.3",
           "JAX_PLATFORMS": "cpu"}
    proc = subprocess.run([sys.executable, "-c", _DROP_SCRIPT], env=env,
                          capture_output=True, text=True, timeout=240)
    out = proc.stdout + proc.stderr
    assert proc.returncode == 0, out[-4000:]
    assert "DROP_OK" in out, out[-4000:]
    assert "dedup: replayed push" in out, \
        "no server-side dedup fired - replay path untested?"


# --------------------------------------------------------------------- #
# multi-worker partial-reply window (PR-6 documented limitation, now
# guarded): after a migration, a worker that consumed round N's reply
# pushes N+1 while a worker whose reply was lost re-pushes N — the
# server must never silently sum the two rounds into one aggregate.
# --------------------------------------------------------------------- #


@pytest.mark.chaos
def test_round_skew_rejected_never_missummed():
    """The round-alignment gate (native RoundAligned): a sync-mode
    stamped fold carrying a different round than the one that opened
    the aggregation round is REJECTED with an error reply (and a
    round_skew flight event) — the silent cross-round mis-sum the
    partial-reply window used to produce is now a loud, attributable
    failure."""
    port, t = _server_thread(num_workers=2)
    addr = [f"127.0.0.1:{port}"]
    c0 = PSClient(addr, worker_id=0)
    c1 = PSClient(addr, worker_id=1)
    n = 256
    key = 9
    x0 = np.arange(n, dtype=np.float32)
    x1 = np.full(n, 5.0, np.float32)

    th = threading.Thread(
        target=c0.init_key, args=(0, key, np.zeros(n, np.float32),
                                  CMD_F32), daemon=True)
    th.start()
    c1.init_key(0, key, np.zeros(n, np.float32), CMD_F32)
    th.join(timeout=15)
    assert not th.is_alive()

    # aligned round folds normally
    c0.zpush(0, key, x0, CMD_F32, epoch=_epoch(1))
    c1.zpush(0, key, x1, CMD_F32, epoch=_epoch(1))
    out = np.empty(n, np.float32)
    c0.zpull(0, key, out, CMD_F32, exact=True)
    np.testing.assert_array_equal(out, x0 + x1)

    # the partial-reply-window shape: w1 opens round 2, w0 (which
    # "consumed" round 2 elsewhere) pushes round 3 into the SAME
    # positional round — must be rejected, not summed
    c1.zpush(0, key, x1 * 2, CMD_F32, epoch=_epoch(2))
    with pytest.raises(RuntimeError):
        c0.zpush(0, key, x0 * 2, CMD_F32, epoch=_epoch(3))
    # the guard recorded the skew on the flight plane
    evs = c1.drain_flight(0)
    assert any(e["kind"] == "round_skew" for e in evs), evs
    # w0 re-sending the ALIGNED round still completes it correctly —
    # the gate rejects skew, it never poisons the round
    c0.zpush(0, key, x0 * 2, CMD_F32, epoch=_epoch(2))
    c0.zpull(0, key, out, CMD_F32, exact=True)
    np.testing.assert_array_equal(out, (x0 + x1) * 2)

    c0.close()  # both workers SHUTDOWN: the 2-worker server exits
    c1.close()
    t.join(timeout=10)


@pytest.mark.chaos
def test_benign_window_migration_recovers_bitwise():
    """The DOMINANT window (2-worker subprocess drill, satellite 1):
    the server dies mid-round — neither worker consumed the round —
    and both re-push the SAME round on the adoptive server. The
    replay-epoch machinery covers this case exactly: both folds apply
    once on the fresh store, the aggregate is bitwise the true sum,
    and a later replay of the same round is deduped."""
    from byteps_tpu.utils.net import free_port

    port_a = free_port()
    # victim: a REAL process (SIGKILL-able); survivor: in-process
    proc = _spawn_server_proc(port_a, num_workers=2, num_servers=2)
    port_b, tb = _server_thread(num_workers=2)
    _wait_ports([port_a, port_b])
    addrs = [f"127.0.0.1:{port_a}", f"127.0.0.1:{port_b}"]
    c0 = PSClient(addrs, worker_id=0)
    c1 = PSClient(addrs, worker_id=1)
    n = 512
    key = 4
    x0 = np.arange(n, dtype=np.float32)
    x1 = np.full(n, 3.0, np.float32)
    try:
        th = threading.Thread(
            target=c0.init_key, args=(0, key, np.zeros(n, np.float32),
                                      CMD_F32), daemon=True)
        th.start()
        c1.init_key(0, key, np.zeros(n, np.float32), CMD_F32)
        th.join(timeout=15)
        assert not th.is_alive()

        # round 1 completes on the victim
        c0.zpush(0, key, x0, CMD_F32, epoch=_epoch(1))
        c1.zpush(0, key, x1, CMD_F32, epoch=_epoch(1))
        out = np.empty(n, np.float32)
        c0.zpull(0, key, out, CMD_F32, exact=True)
        c1.zpull(0, key, out, CMD_F32, exact=True)

        # round 2: w0's push folds on the victim... which then dies
        # before the round completes — the benign (mid-round) window
        c0.zpush(0, key, x0 * 2, CMD_F32, epoch=_epoch(2))
        os.kill(proc.pid, signal.SIGKILL)
        proc.wait(timeout=10)
        time.sleep(0.3)  # EOF propagates to every striped conn
        assert c0.server_dead(0) and c1.server_dead(0)

        # "migration": both workers re-home the key to the survivor
        # (index 1) — fresh store via the init barrier, then BOTH
        # re-push round 2 (w0's retry chain still holds the payload)
        th = threading.Thread(
            target=c0.init_key, args=(1, key, np.zeros(n, np.float32),
                                      CMD_F32), daemon=True)
        th.start()
        c1.init_key(1, key, np.zeros(n, np.float32), CMD_F32)
        th.join(timeout=15)
        assert not th.is_alive()
        c0.zpush(1, key, x0 * 2, CMD_F32, epoch=_epoch(2, attempt=1))
        c1.zpush(1, key, x1 * 2, CMD_F32, epoch=_epoch(2))
        c0.zpull(1, key, out, CMD_F32, exact=True)
        np.testing.assert_array_equal(out, (x0 + x1) * 2)  # TRUE sum
        c1.zpull(1, key, out, CMD_F32, exact=True)
        np.testing.assert_array_equal(out, (x0 + x1) * 2)

        # and a replayed round-2 push on the adoptive server is
        # deduped (answered, never re-folded): round 3 still exact
        c0.zpush(1, key, x0 * 2, CMD_F32, epoch=_epoch(2, attempt=2))
        c0.zpush(1, key, x0 * 3, CMD_F32, epoch=_epoch(3))
        c1.zpush(1, key, x1 * 3, CMD_F32, epoch=_epoch(3))
        c0.zpull(1, key, out, CMD_F32, exact=True)
        np.testing.assert_array_equal(out, (x0 + x1) * 3)
    finally:
        # both workers send SHUTDOWN so the 2-worker survivor exits
        # (the dead victim's shutdown request fails fast on dead conns)
        c0.close()
        c1.close()
        if proc.poll() is None:
            proc.kill()
        proc.wait(timeout=10)
        tb.join(timeout=10)


# --------------------------------------------------------------------- #
# THE churn test: SIGKILL one of two servers mid-training
# --------------------------------------------------------------------- #


@pytest.mark.chaos
def test_server_churn_failover_numerics(tmp_path):
    """Acceptance churn test: with 2 loopback server PROCESSES, SIGKILL
    one mid-run. The run completes without restart, every round's
    aggregate matches the no-churn expectation bitwise (1 worker: the
    aggregate IS the pushed tensor — the migration design re-inits and
    re-pushes on the survivor, so no summation reorders),
    ``wire/server_failovers`` >= 1, and no handles or arena leases
    leak."""
    from byteps_tpu.core.state import GlobalState
    from byteps_tpu.utils.net import free_port

    ports = []
    while len(ports) < 2:
        p = free_port()
        if p not in ports:
            ports.append(p)
    env_keys = {
        "DMLC_NUM_WORKER": "1", "DMLC_NUM_SERVER": "2",
        "DMLC_PS_ROOT_URI": "127.0.0.1",
        "DMLC_PS_ROOT_PORT": str(ports[0]),
        "BYTEPS_SERVER_HOSTS": ",".join(f"127.0.0.1:{p}" for p in ports),
        "BYTEPS_FORCE_DISTRIBUTED": "1",
        "BYTEPS_WIRE_BACKOFF_MS": "25",
    }
    saved = {k: os.environ.get(k) for k in env_keys}
    os.environ.update(env_keys)
    procs = [_spawn_server_proc(p, num_workers=1, num_servers=2)
             for p in ports]
    bps = None
    try:
        _wait_ports(ports)
        GlobalState._instance = None
        import byteps_tpu as bps
        bps.init()
        from byteps_tpu.core.state import get_state
        state = get_state()

        rng = np.random.RandomState(11)
        grads = [rng.randn(2048).astype(np.float32) for _ in range(8)]
        # host-compressed leaf riding the same churn (PR-6 limitation
        # closed: COMP_INIT state used to die with the server and
        # compressed keys failed over with a hard error; the retry path
        # now re-installs the compressor on the adoptive server).
        # lossless tier: failover numerics stay BITWISE comparable.
        from byteps_tpu.server.compressed import CompressedRegistry
        comp_reg = CompressedRegistry(state.ps_client, 1,
                                      {"compressor": "lossless"})
        cgrad = rng.randn(4096).astype(np.float32)

        def run_round(r):
            hs = [bps.push_pull_async(g * (r + 1), f"churn{i}",
                                      average=False)
                  for i, g in enumerate(grads)]
            ch = comp_reg.push_pull_async(state, "churn_comp",
                                          cgrad * (r + 1), average=False)
            out = [np.array(bps.synchronize(h, timeout=120)) for h in hs]
            cout = np.array(bps.synchronize(ch, timeout=120))
            return out, cout

        # warm rounds: declare keys, init barrier, steady state
        for r in range(2):
            res, cres = run_round(r)
            for g, o in zip(grads, res):
                np.testing.assert_array_equal(o, g * (r + 1))
            np.testing.assert_array_equal(cres, cgrad * (r + 1))

        # pick a victim that actually owns keys, and confirm BOTH
        # servers hold some (otherwise the kill proves nothing)
        owners = {p.server
                  for ctx in state.registry.contexts_in_order()
                  for p in ctx.partitions}
        assert owners == {0, 1}, f"keys not spread: {owners}"
        victim = 1

        # mid-round kill: submit first (compressed leaf included),
        # SIGKILL while in flight
        hs = [bps.push_pull_async(g * 3.0, f"churn{i}", average=False)
              for i, g in enumerate(grads)]
        ch = comp_reg.push_pull_async(state, "churn_comp", cgrad * 3.0,
                                      average=False)
        os.kill(procs[victim].pid, signal.SIGKILL)
        procs[victim].wait(timeout=10)
        for g, h in zip(grads, hs):
            np.testing.assert_array_equal(
                np.array(bps.synchronize(h, timeout=120)), g * 3.0)
        # the compressed leaf survives the death like the dense ones:
        # its retry re-init-pushes AND re-COMP_INITs on the survivor
        np.testing.assert_array_equal(
            np.array(bps.synchronize(ch, timeout=120)), cgrad * 3.0)

        # training continues: later rounds all route to the survivor
        for r in range(3, 5):
            res, cres = run_round(r)
            for g, o in zip(grads, res):
                np.testing.assert_array_equal(o, g * (r + 1))
            np.testing.assert_array_equal(cres, cgrad * (r + 1))

        snap = bps.get_metrics()
        assert snap["counters"]["wire/server_failovers"] >= 1
        assert snap["counters"]["registry/migrations"] >= 1
        assert snap["counters"]["wire/retries"] >= 1
        assert state.registry.dead_servers() == [victim]
        for ctx in state.registry.contexts_in_order():
            for p in ctx.partitions:
                assert p.server != victim

        # flight recorder captured the failover CAUSALLY (PR 12): the
        # worker ring holds retry -> failover -> per-key migration
        # events in timestamp order, key-matched to the routing table
        from byteps_tpu.core import flight as flight_mod
        evs = flight_mod.get_recorder().events()
        kinds = [e["kind"] for e in evs]
        assert "wire_retry" in kinds, kinds
        assert "server_failover" in kinds, kinds
        assert "key_migration" in kinds, kinds
        ts = [e["ts_ns"] for e in evs]
        assert ts == sorted(ts), "flight events out of causal order"
        fo = next(e for e in evs if e["kind"] == "server_failover")
        first_retry = next(e["ts_ns"] for e in evs
                           if e["kind"] == "wire_retry")
        assert fo["ts_ns"] >= first_retry, \
            "failover recorded before the retry that triggered it"
        assert fo["key"] == victim  # failover names the dead server
        migrated_keys = {e["key"] for e in evs
                         if e["kind"] == "key_migration"}
        assert migrated_keys, "no per-key migration events"
        live_keys = {p.key for ctx in state.registry.contexts_in_order()
                     for p in ctx.partitions}
        assert migrated_keys <= live_keys, \
            "migration events name keys the registry does not know"
        # and the merged dump (worker + surviving server) is written,
        # valid JSON, and stays causally ordered after clock alignment
        import json as _json
        dump_path = bps.dump_flight_record(
            str(tmp_path / "churn-flight.json"))
        assert dump_path and os.path.exists(dump_path)
        with open(dump_path) as f:
            doc = _json.load(f)
        merged_ts = [e["ts_ns"] for e in doc["merged"]]
        assert merged_ts == sorted(merged_ts)
        assert any(e["kind"] == "server_failover" for e in doc["merged"])

        # zero leaks: handles cleared, no busy arena slots (poll
        # briefly — the completion-ordered drain releases leases at the
        # next checkout boundary)
        deadline = time.monotonic() + 10
        busy = handles = None
        while time.monotonic() < deadline:
            with state.arena._mu:
                busy = [k for k, s in state.arena._slots.items()
                        if s.busy]
            handles = dict(state.handles._handles)
            if not busy and not handles:
                break
            time.sleep(0.1)
        assert not busy, f"leaked arena leases: {busy[:8]}"
        assert not handles, f"leaked handles: {list(handles)[:8]}"
    finally:
        try:
            if bps is not None:
                bps.shutdown()
        except Exception:
            pass
        GlobalState._instance = None
        for p in procs:
            if p.poll() is None:
                p.kill()
            p.wait(timeout=10)
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


@pytest.mark.chaos
def test_dead_fleet_fails_fast(tmp_path):
    """Permanently-dead fleet: every server gone -> a submit fails with
    a clear bounded error well inside the retry x backoff budget — no
    hang (the fail-fast guard riding alongside
    test_failure_detection.py's worker-death semantics). The error
    additionally POINTS AT the flight-record dump (PR 12): the operator
    starts from the causal timeline, not log archaeology."""
    from byteps_tpu.core.state import GlobalState
    from byteps_tpu.utils.net import free_port

    port = free_port()
    env_keys = {
        "DMLC_NUM_WORKER": "1", "DMLC_NUM_SERVER": "1",
        "DMLC_PS_ROOT_URI": "127.0.0.1", "DMLC_PS_ROOT_PORT": str(port),
        "BYTEPS_FORCE_DISTRIBUTED": "1",
        "BYTEPS_WIRE_RETRY": "2", "BYTEPS_WIRE_BACKOFF_MS": "25",
        "BYTEPS_FLIGHT_DIR": str(tmp_path / "flight"),
    }
    saved = {k: os.environ.get(k) for k in env_keys}
    os.environ.update(env_keys)
    proc = _spawn_server_proc(port, num_workers=1, num_servers=1)
    bps = None
    try:
        _wait_ports([port])
        GlobalState._instance = None
        import byteps_tpu as bps
        bps.init()
        x = np.ones(512, np.float32)
        out = bps.synchronize(bps.push_pull_async(x, "ff", average=False),
                              timeout=60)
        np.testing.assert_array_equal(out, x)

        os.kill(proc.pid, signal.SIGKILL)
        proc.wait(timeout=10)
        time.sleep(0.3)  # EOF propagates to every striped conn

        t0 = time.monotonic()
        h = bps.push_pull_async(x * 2, "ff", average=False)
        with pytest.raises((RuntimeError, TimeoutError)) as ei:
            bps.synchronize(h, timeout=60)
        elapsed = time.monotonic() - t0
        assert elapsed < 30, f"dead fleet took {elapsed:.1f}s to fail"
        msg = str(ei.value)
        assert ("attempts" in msg or "fleet is gone" in msg
                or "dead" in msg), msg
        # the fail-fast error names the flight dump, and the dump holds
        # the retry trail that led to the verdict
        assert "flight record dumped to" in msg, msg
        dump_path = msg.rsplit("flight record dumped to ", 1)[1].strip()
        assert os.path.exists(dump_path), dump_path
        import json as _json
        with open(dump_path) as f:
            doc = _json.load(f)
        kinds = [e["kind"] for e in doc["worker"]["events"]]
        assert "wire_retry" in kinds, kinds
        assert "round_failed" in kinds, kinds
    finally:
        try:
            if bps is not None:
                bps.shutdown()
        except Exception:
            pass
        GlobalState._instance = None
        if proc.poll() is None:
            proc.kill()
        proc.wait(timeout=10)
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
