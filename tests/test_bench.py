"""Orchestrator-level tests for bench.py's wedge-proof attempt schedule.

The real phases are exercised elsewhere (loopback PS tests, train tests);
here the subprocess runner is stubbed so the SCHEDULE itself is testable
in milliseconds: device attempts spread across the CPU phases, the
device-tier wire phase decoupled from train, the tunnel_diag trail, and
the budget-bounded final wait (the round-3 failure mode: two contiguous
attempts inside one wedge window captured nothing).
"""

from __future__ import annotations

import importlib.util
import json
import os
import sys

import pytest


@pytest.fixture()
def bench(monkeypatch):
    spec = importlib.util.spec_from_file_location(
        "bench", os.path.join(os.path.dirname(__file__), "..", "bench.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    monkeypatch.setattr(mod.time, "sleep", lambda s: None)
    # the schedule math (final-round cap) keys off the default budget
    monkeypatch.delenv("BENCH_BUDGET_S", raising=False)
    return mod


def run_main(bench, monkeypatch, capsys, phase_script):
    """Drive bench.main() with a scripted _run_phase; returns the final
    JSON line. ``phase_script(name, calls)`` -> (result|None, err|None)."""
    calls = []

    def fake_run_phase(name, timeout_s):
        out = phase_script(name, calls)
        calls.append(name)
        return out

    monkeypatch.setattr(bench, "_run_phase", fake_run_phase)
    monkeypatch.setattr(sys, "argv", ["bench.py"])
    bench.main()
    line = capsys.readouterr().out.strip().splitlines()[-1]
    return json.loads(line), calls


def test_healthy_tunnel_lands_everything(bench, monkeypatch, capsys):
    def script(name, calls):
        if name == "probe":
            return {"ok": True, "platform": "tpu"}, None
        if name == "train":
            return {"value": 100000.0, "mfu": 0.4,
                    "train_variant": "remat"}, None
        if name == "pushpull_tpu":
            return {"pushpull_onebit_tpu_gbps": 9.0,
                    "pushpull_dense_tpu_gbps": 4.0}, None
        if name == "pushpull":
            return {"pushpull_dense_gbps": 3.0,
                    "pushpull_onebit_gbps": 3.3,
                    "pushpull_randomk_gbps": 3.7}, None
        if name == "pushpull_2srv":
            return {"pushpull_dense_2srv_gbps": 2.7}, None
        if name == "pushpull_throttled":
            return {"pushpull_throttled_1srv_gbps": 0.1,
                    "pushpull_throttled_2srv_gbps": 0.2,
                    "throttle_mbps": 100.0}, None
        if name == "arena_ab":
            return {"arena_on_step_ms": 5.0,
                    "arena_off_step_ms": 6.5}, None
        if name == "metrics_ab":
            return {"metrics_on_step_ms": 5.1,
                    "metrics_off_step_ms": 5.0,
                    "metrics_overhead_pct": 2.0}, None
        if name == "trace_ab":
            return {"trace_on_step_ms": 5.05,
                    "trace_off_step_ms": 5.0,
                    "trace_overhead_pct": 1.0,
                    "trace_server_records": 96,
                    "trace_rid_links": 24}, None
        if name == "ledger_ab":
            return {"ledger_on_step_ms": 5.08,
                    "ledger_off_step_ms": 5.0,
                    "ledger_overhead_pct": 1.6,
                    "ledger_mfu": 0.31,
                    "ledger_overlap_frac": 0.62,
                    "ledger_wire_efficiency": 0.52,
                    "ledger_cost_source": "xla",
                    "ledger_verdict_named": True}, None
        if name == "health_ab":
            return {"health_on_step_ms": 5.06,
                    "health_off_step_ms": 5.0,
                    "health_overhead_pct": 1.2,
                    "health_grad_norm": 0.031,
                    "health_update_ratio_p95": 2.1e-4,
                    "health_nonfinite_leaves": 0,
                    "health_infold_rounds": 48,
                    "health_verdict_named": True}, None
        if name == "stream_ab":
            return {"stream_on_step_ms": 4.0,
                    "stream_off_step_ms": 4.8,
                    "stream_ttfp_on_ms": 0.9,
                    "stream_ttfp_off_ms": 3.1}, None
        if name == "barrier_ab":
            return {"barrier_on_step_ms": 3.4,
                    "barrier_off_step_ms": 4.6,
                    "barrier_speedup": 1.353,
                    "barrier_overlap_on_frac": 0.71,
                    "barrier_overlap_off_frac": 0.12,
                    "barrier_carried_leaves": 96,
                    "barrier_carry_drained": 96,
                    "barrier_sync_carried_leaves": 0}, None
        if name == "wire_ab":
            return {"wire_fused_step_ms": 3.6,
                    "wire_twoop_step_ms": 4.1,
                    "wire_fused_requests": 72,
                    "wire_twoop_requests": 144,
                    "wire_request_ratio": 0.5,
                    "wire_half_proof": True}, None
        if name == "fold_ab":
            return {"fold_simd_gbps": 6.1,
                    "fold_scalar_gbps": 3.2,
                    "fold_simd_tier": 3,
                    "fold_bytes_per_arm": 805306368,
                    "fold_bytes_equal": True,
                    "fold_direct_recvs": 96,
                    "fold_oob_msgs": 120}, None
        if name == "shard_ab":
            return {"shard_on_step_ms": 3.9,
                    "shard_off_step_ms": 4.2,
                    "shard_local_size": 8,
                    "shard_bytes_per_device_on": 3145728,
                    "shard_bytes_per_device_off": 25165824,
                    "shard_reduction_ratio": 8.0,
                    "shard_counter_proof": True}, None
        if name == "scaling":
            return {"scaling_efficiency_2w": 0.45}, None
        if name == "churn_ab":
            return {"churn_ab_identical": True,
                    "churn_ab_chaos_retries": 7,
                    "churn_ab_clean_retries": 0,
                    "churn_ab_drop_rate": 0.25,
                    "churn_ab_idempotent_proof": True}, None
        if name == "scaleup_ab":
            return {"scaleup_before_step_ms": 320.0,
                    "scaleup_after_step_ms": 180.0,
                    "scaleup_ratio": 0.5625,
                    "scaleup_joins": 1,
                    "scaleup_newcomer_bytes": 16777216,
                    "scaleup_identical": True,
                    "scaleup_proof": True}, None
        if name == "codec_adapt_ab":
            return {"codec_adapt_throttled_switches": 2,
                    "codec_adapt_unthrottled_switches": 0,
                    "codec_adapt_wire_bytes": 100,
                    "codec_dense_wire_bytes": 400,
                    "codec_adapt_wire_reduction": 0.25,
                    "codec_lossless_bytes_post": 12345,
                    "codec_lossless_bitwise": True,
                    "codec_tag_mismatch_rejected": True,
                    "codec_adapt_proof": True}, None
        if name == "stripe_ab":
            return {"stripe_ab_legacy_gbps": 1.87,
                    "stripe_ab_ring_gbps": 1.89,
                    "stripe_ab_striped_gbps": 1.83,
                    "stripe_ab_speedup": 0.98,
                    "stripe_ab_segs": 4096,
                    "stripe_ab_msgs_per_batch": 1.23,
                    "stripe_ab_conservation": True,
                    "stripe_ab_throttled_dense_gbps": 0.02,
                    "stripe_ab_throttled_lossless_gbps": 0.042,
                    "stripe_ab_lossless_gain": 2.09,
                    "stripe_ab_throttle_mbps": 20.0}, None
        if name == "ts_ab":
            return {"ts_on_step_ms": 5.02,
                    "ts_off_step_ms": 5.0,
                    "ts_overhead_pct": 0.4,
                    "ts_series_count": 72,
                    "ts_stripe_lane_points": 48,
                    "ts_staleness_points": 20,
                    "ts_engaged_proof": True}, None
        raise AssertionError(name)

    out, calls = run_main(bench, monkeypatch, capsys, script)
    assert out["value"] == 100000.0
    assert out["churn_ab_idempotent_proof"] is True
    assert out["churn_ab_chaos_retries"] == 7
    # never-landed driver keys run FIRST (the VERDICT next-round #3
    # reorder): the throttled pair and scaling ahead of the long raw
    # pushpull phases that used to starve them out of overrun rounds
    cpu_calls = [c for c in calls
                 if c not in ("probe", "train", "pushpull_tpu")]
    assert cpu_calls[:10] == ["pushpull_throttled", "scaling", "churn_ab",
                              "scaleup_ab", "codec_adapt_ab", "stripe_ab",
                              "fold_ab", "ledger_ab", "health_ab",
                              "ts_ab"]
    assert out["stripe_ab_conservation"] is True
    assert out["stripe_ab_lossless_gain"] == 2.09
    assert out["stripe_ab_segs"] == 4096
    assert out["scaleup_proof"] is True
    assert out["scaleup_joins"] == 1
    assert out["scaleup_newcomer_bytes"] == 16777216
    assert out["codec_adapt_proof"] is True
    assert out["codec_adapt_throttled_switches"] == 2
    assert out["codec_adapt_unthrottled_switches"] == 0
    assert out["codec_lossless_bitwise"] is True
    assert out["codec_tag_mismatch_rejected"] is True
    assert out["metrics_on_step_ms"] == 5.1
    assert out["metrics_overhead_pct"] == 2.0
    assert out["ledger_on_step_ms"] == 5.08
    assert out["ledger_overhead_pct"] == 1.6
    assert out["ledger_mfu"] == 0.31
    assert out["ledger_overlap_frac"] == 0.62
    assert out["ledger_wire_efficiency"] == 0.52
    assert out["health_on_step_ms"] == 5.06
    assert out["health_overhead_pct"] == 1.2
    assert out["health_grad_norm"] == 0.031
    assert out["health_infold_rounds"] == 48
    assert out["trace_on_step_ms"] == 5.05
    assert out["trace_overhead_pct"] == 1.0
    assert out["trace_server_records"] == 96
    assert out["trace_rid_links"] == 24
    assert out["stream_on_step_ms"] == 4.0
    assert out["stream_ttfp_on_ms"] == 0.9
    assert out["barrier_on_step_ms"] == 3.4
    assert out["barrier_overlap_on_frac"] == 0.71
    assert out["barrier_carried_leaves"] == 96
    assert out["wire_fused_step_ms"] == 3.6
    assert out["wire_request_ratio"] == 0.5
    assert out["fold_simd_gbps"] == 6.1
    assert out["fold_bytes_equal"] is True
    assert out["shard_on_step_ms"] == 3.9
    assert out["shard_reduction_ratio"] == 8.0
    assert out["pushpull_throttled_2srv_gbps"] == 0.2
    assert out["arena_on_step_ms"] == 5.0
    assert out["vs_baseline"] == round(100000.0 / 51810.0, 4)
    assert out["pushpull_onebit_tpu_gbps"] == 9.0
    assert "phase_errors" not in out
    # exactly one probe+train+tpu up front, then the CPU phases
    assert calls[:3] == ["probe", "train", "pushpull_tpu"]
    assert calls.count("train") == 1
    assert out["tunnel_diag"][0]["at"] == "start"


def test_wedged_tunnel_emits_nulls_and_diag(bench, monkeypatch, capsys):
    def script(name, calls):
        if name == "probe":
            # the staged probe ATTRIBUTES the wedge (the BENCH_r03-r05
            # rc=3 class): stage name + real traceback in the result
            return {"ok": False, "stage": "tiny_ones",
                    "error": ("Traceback (most recent call last):\n"
                              "  ...\nRuntimeError: backend wedged in "
                              "jnp.ones")}, None
        if name in ("train", "pushpull_tpu"):
            raise AssertionError("device phase must not run unprobed")
        if name == "pushpull":
            return {"pushpull_dense_gbps": 3.0,
                    "pushpull_onebit_gbps": 3.3,
                    "pushpull_randomk_gbps": 3.7}, None
        if name == "pushpull_2srv":
            return {"pushpull_dense_2srv_gbps": 2.7}, None
        if name == "pushpull_throttled":
            return {"pushpull_throttled_1srv_gbps": 0.1,
                    "pushpull_throttled_2srv_gbps": 0.2,
                    "throttle_mbps": 100.0}, None
        if name == "arena_ab":
            return {"arena_on_step_ms": 5.0,
                    "arena_off_step_ms": 6.5}, None
        if name == "metrics_ab":
            return {"metrics_on_step_ms": 5.1,
                    "metrics_off_step_ms": 5.0,
                    "metrics_overhead_pct": 2.0}, None
        if name == "trace_ab":
            return {"trace_on_step_ms": 5.05,
                    "trace_off_step_ms": 5.0,
                    "trace_overhead_pct": 1.0}, None
        if name == "ledger_ab":
            return {"ledger_on_step_ms": 5.08,
                    "ledger_off_step_ms": 5.0,
                    "ledger_overhead_pct": 1.6,
                    "ledger_mfu": 0.02}, None
        if name == "health_ab":
            return {"health_on_step_ms": 5.06,
                    "health_off_step_ms": 5.0,
                    "health_overhead_pct": 1.2,
                    "health_grad_norm": 0.03,
                    "health_infold_rounds": 12}, None
        if name == "stream_ab":
            return {"stream_on_step_ms": 4.0,
                    "stream_off_step_ms": 4.8}, None
        if name == "barrier_ab":
            return {"barrier_on_step_ms": 3.4,
                    "barrier_off_step_ms": 4.6,
                    "barrier_carried_leaves": 96}, None
        if name == "wire_ab":
            return {"wire_fused_step_ms": 3.6,
                    "wire_twoop_step_ms": 4.1,
                    "wire_request_ratio": 0.5}, None
        if name == "fold_ab":
            return {"fold_simd_gbps": 6.1,
                    "fold_scalar_gbps": 3.2,
                    "fold_bytes_equal": True}, None
        if name == "shard_ab":
            return {"shard_on_step_ms": 3.9,
                    "shard_off_step_ms": 4.2,
                    "shard_reduction_ratio": 8.0}, None
        if name == "ts_ab":
            return {"ts_on_step_ms": 5.02,
                    "ts_off_step_ms": 5.0,
                    "ts_overhead_pct": 0.4,
                    "ts_engaged_proof": True}, None
        if name == "scaling":
            return {"scaling_efficiency_2w": 0.45}, None
        if name == "churn_ab":
            return {"churn_ab_identical": True,
                    "churn_ab_chaos_retries": 5,
                    "churn_ab_clean_retries": 0}, None
        if name == "scaleup_ab":
            return {"scaleup_before_step_ms": 320.0,
                    "scaleup_after_step_ms": 180.0,
                    "scaleup_joins": 1,
                    "scaleup_proof": True}, None
        if name == "codec_adapt_ab":
            return {"codec_adapt_throttled_switches": 1,
                    "codec_adapt_unthrottled_switches": 0,
                    "codec_adapt_wire_reduction": 0.5,
                    "codec_adapt_proof": True}, None
        if name == "stripe_ab":
            return {"stripe_ab_striped_gbps": 1.83,
                    "stripe_ab_conservation": True,
                    "stripe_ab_lossless_gain": 2.09}, None
        raise AssertionError(name)

    out, calls = run_main(bench, monkeypatch, capsys, script)
    assert out["value"] is None and out["mfu"] is None
    # CPU numbers still land
    assert out["pushpull_dense_gbps"] == 3.0
    assert out["phase_errors"]["probe"].startswith("bad probe")
    # attempts spread across the run: start + after each CPU phase +
    # budget-derived final rounds (the loop keeps retrying while budget
    # remains — ending with unused budget is strictly worse; the cap is
    # int(budget/150)+4 so a mocked clock cannot spin forever; cheap
    # 40-60s probes mean a real wedged round fits ~12-16 attempts)
    # LITERAL, not the implementation's formula: if bench.py's cap
    # derivation drifts (e.g. //15 spinning 140 probes), this catches it
    n_final = 18
    # start + one attempt after each of the 19 CPU phases + finals
    assert calls.count("probe") == 20 + n_final
    probes = [d for d in out["tunnel_diag"] if "probe_wall_s" in d]
    assert [d["at"] for d in probes] == [
        "start", "after_pushpull_throttled", "after_scaling",
        "after_churn_ab", "after_scaleup_ab", "after_codec_adapt_ab",
        "after_stripe_ab",
        "after_fold_ab", "after_ledger_ab", "after_health_ab",
        "after_ts_ab",
        "after_pushpull", "after_pushpull_2srv",
        "after_arena_ab", "after_metrics_ab", "after_trace_ab",
        "after_stream_ab", "after_barrier_ab", "after_wire_ab",
        "after_shard_ab",
        *[f"final_{i}" for i in range(1, n_final + 1)]]
    # the wedged stage and its traceback ride every diag entry — a dead
    # round is attributable from BENCH_rNN.json alone
    assert all(d.get("probe_stage") == "tiny_ones" for d in probes)
    assert all("RuntimeError: backend wedged" in d.get("probe_error", "")
               for d in probes)
    assert any(str(d.get("at", "")).startswith("final_wait")
               for d in out["tunnel_diag"])


def test_phase_probe_attributes_wedges(bench, monkeypatch):
    """The staged probe (the BENCH_r03-r05 rc=3 wedge satellite): a
    healthy backend passes all three stages; a RAISING stage returns
    the real traceback; a HUNG stage returns within its own deadline
    carrying the worker's live stack — never a bare watchdog kill."""
    out = bench.phase_probe()
    assert out["ok"] is True and out["stage"] == "done"
    assert out["tiny_ok"] is True

    def boom():
        raise RuntimeError("tunnel wedged in jnp.ones")

    monkeypatch.setattr(bench, "_setup_device_backend", boom)
    out = bench.phase_probe()
    assert out["ok"] is False and out["stage"] == "backend"
    assert "RuntimeError: tunnel wedged" in out["error"]

    import threading as _t

    monkeypatch.setenv("BENCH_PROBE_STAGE_S", "0.5")
    monkeypatch.setattr(bench, "_setup_device_backend",
                        lambda: _t.Event().wait())  # hangs forever
    out = bench.phase_probe()
    assert out["ok"] is False and out["stage"] == "backend"
    assert "hung" in out["error"] and "Event().wait()" in out["error"]


def test_late_recovery_lands_train(bench, monkeypatch, capsys):
    """Tunnel recovers after the scaling phase: attempt 4 captures the
    headline, and pushpull_tpu lands in the same attempt."""
    def script(name, calls):
        if name == "probe":
            healthy = calls.count("probe") >= 3
            return ({"ok": True, "platform": "tpu"}, None) if healthy \
                else (None, "timeout")
        if name == "train":
            return {"value": 90000.0, "mfu": 0.38,
                    "train_variant": "remat"}, None
        if name == "pushpull_tpu":
            return {"pushpull_onebit_tpu_gbps": 8.0,
                    "pushpull_dense_tpu_gbps": 4.0}, None
        return {}, None

    out, calls = run_main(bench, monkeypatch, capsys, script)
    assert out["value"] == 90000.0
    assert out["pushpull_onebit_tpu_gbps"] == 8.0
    assert "probe" not in out.get("phase_errors", {})
    assert "train" not in out.get("phase_errors", {})
    assert calls.count("probe") == 4  # recovered on the 4th, no final


def test_tpu_wire_decoupled_from_train_failure(bench, monkeypatch, capsys):
    """Probe healthy but train fails (e.g. OOM): the device-tier wire
    number must land anyway — the round-3 gating lost it."""
    def script(name, calls):
        if name == "probe":
            return {"ok": True, "platform": "tpu"}, None
        if name == "train":
            return None, "rc=1"
        if name == "pushpull_tpu":
            return {"pushpull_onebit_tpu_gbps": 8.5,
                    "pushpull_dense_tpu_gbps": 4.2}, None
        return {}, None

    out, calls = run_main(bench, monkeypatch, capsys, script)
    assert out["value"] is None
    assert out["pushpull_onebit_tpu_gbps"] == 8.5
    assert out["phase_errors"]["train"] == "rc=1"
    # train retried on later attempts, wire phase ran exactly once
    assert calls.count("pushpull_tpu") == 1
    assert calls.count("train") >= 2


def test_scaling_summary_estimator(bench):
    """The scaling estimator's contract: headline = best WITHIN-rep
    ratio (never a cross-rep pairing), spread/reps keys derived from
    pairs only, and the list-maxima fallback when no rep completed both
    configs."""
    # three clean interleaved reps on a 1-core host (cap = 0.5)
    out = bench._scaling_summary(
        pairs=[(100.0, 90.0), (110.0, 88.0), (105.0, 94.0)],
        t1s=[100.0, 110.0, 105.0], tns=[90.0, 88.0, 94.0],
        workers=2, cores=1)
    # per-rep ratios: 0.45, 0.4, 0.4476 -> best 0.45
    assert out["scaling_efficiency_2w"] == 0.45
    assert out["scaling_vs_core_cap"] == 0.9
    assert out["scaling_vs_cap_reps"] == [0.9, 0.8, 0.8952]
    assert out["scaling_spread"] == round((0.45 - 0.4) / 0.5, 4)
    # asymmetric failures: rep2 lost its t1, rep3 lost its tn — the one
    # complete pair decides the headline; the stray 120.0 t1 and 99.0 tn
    # (which a zip over the flat lists would have married into a bogus
    # 99/(2*120) or 120-based ratio) must NOT combine
    out = bench._scaling_summary(
        pairs=[(100.0, 90.0)],
        t1s=[100.0, 120.0], tns=[90.0, 99.0],
        workers=2, cores=1)
    assert out["scaling_efficiency_2w"] == 0.45
    assert "scaling_vs_cap_reps" not in out  # single pair: no band
    # no complete pair at all: fall back to the ratio of list maxima
    out = bench._scaling_summary(
        pairs=[], t1s=[100.0], tns=[80.0], workers=2, cores=1)
    assert out["scaling_efficiency_2w"] == 0.4
    # degenerate: zero t1 measurements guard the division
    out = bench._scaling_summary(
        pairs=[(0.0, 50.0)], t1s=[0.0], tns=[50.0], workers=2, cores=1)
    assert out["scaling_efficiency_2w"] == 0.0


def test_cpu_fallback_platform_rejected(bench, monkeypatch, capsys):
    """A silent jax CPU fallback must not publish CPU tokens/s as the
    device headline (unless BENCH_ALLOW_CPU)."""
    def script(name, calls):
        if name == "probe":
            return {"ok": True, "platform": "cpu"}, None
        if name in ("train", "pushpull_tpu"):
            raise AssertionError("device phase ran on a cpu probe")
        return {}, None

    out, _ = run_main(bench, monkeypatch, capsys, script)
    assert out["value"] is None
    assert "cpu" in out["phase_errors"]["probe"]


def test_budget_gate_skips_everything_when_spent(bench, monkeypatch,
                                                 capsys):
    """Round-5 envelope bug regression: with no budget left, NO phase
    may launch (previously the CPU phases ran to their full deadlines
    regardless), and the final JSON line still parses with the skips
    recorded."""
    monkeypatch.setenv("BENCH_BUDGET_S", "1")

    def script(name, calls):
        raise AssertionError(f"phase {name!r} launched on a spent budget")

    out, calls = run_main(bench, monkeypatch, capsys, script)
    assert calls == []
    assert out["value"] is None
    skipped = {k: v for k, v in out["phase_errors"].items()
               if v == "skipped-budget"}
    assert set(skipped) == {"pushpull", "pushpull_2srv",
                            "pushpull_throttled", "churn_ab",
                            "scaleup_ab", "codec_adapt_ab", "stripe_ab",
                            "fold_ab", "ledger_ab", "health_ab",
                            "ts_ab", "arena_ab", "metrics_ab",
                            "trace_ab", "stream_ab", "barrier_ab",
                            "wire_ab", "shard_ab", "scaling"}


def test_multichip_envelope_bounded():
    """MULTICHIP envelope guard (the BENCH_r05 class, applied to the
    dryrun): the dryrun's worst case — every phase running to its full
    per-phase timeout — must fit HALF the driver window, so phase growth
    without budget fails here, in tier-1, instead of silently pushing a
    future driver round past its kill deadline. Also pins the phase
    list to the functions that actually exist (a renamed/removed phase
    fn breaks the product silently otherwise)."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "__graft_entry__",
        os.path.join(os.path.dirname(__file__), "..", "__graft_entry__.py"))
    g = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(g)
    phases = g._DRYRUN_PHASES
    assert len(phases) >= 7  # the envelope covers the real suite
    worst_case = len(phases) * g.DRYRUN_PHASE_TIMEOUT_S
    assert worst_case <= g.DRYRUN_DRIVER_WINDOW_S / 2, (
        f"{len(phases)} dryrun phases x {g.DRYRUN_PHASE_TIMEOUT_S:.0f}s "
        f"= {worst_case:.0f}s worst case exceeds half the "
        f"{g.DRYRUN_DRIVER_WINDOW_S:.0f}s driver window — trim a phase "
        f"or grow the budget DELIBERATELY")
    # the re-exec child's hard timeout mirrors the same half-window
    for name, fn in phases:
        assert callable(fn), name


def test_partial_snapshots_survive_a_kill(bench, monkeypatch, capsys):
    """Every phase flushes the current snapshot as a 'partial'-tagged
    JSON line: an external SIGKILL at ANY point between phases leaves
    the last snapshot as the final parseable line (round 5 lost all its
    numbers to the single end-of-run print)."""
    def script(name, calls):
        if name == "probe":
            return {"ok": True, "platform": "tpu"}, None
        if name == "train":
            return {"value": 90000.0, "mfu": 0.38,
                    "train_variant": "remat"}, None
        if name == "pushpull_tpu":
            return {"pushpull_dense_tpu_gbps": 4.0}, None
        if name == "pushpull":
            return {"pushpull_dense_gbps": 3.0}, None
        return {}, None

    calls2 = []
    monkeypatch.setattr(bench, "_run_phase",
                        lambda n, t: (script(n, calls2),
                                      calls2.append(n))[0])
    monkeypatch.setattr(sys, "argv", ["bench.py"])
    bench.main()
    lines = [json.loads(ln)
             for ln in capsys.readouterr().out.strip().splitlines()
             if ln.startswith("{")]
    assert len(lines) > 2
    assert lines[-1].get("partial") is None          # final: untagged
    assert all(ln.get("partial") for ln in lines[:-1])
    # snapshots accumulate: the headline already rides a mid-run line
    assert any(ln.get("value") == 90000.0 for ln in lines[:-1])
