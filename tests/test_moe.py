"""MoE / expert parallelism: routed layer vs a brute-force per-token oracle,
and the ep-sharded all_to_all path vs the unsharded path."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
from jax import shard_map
from jax.sharding import PartitionSpec as P

from byteps_tpu.models import moe
from byteps_tpu.parallel import sharding as sh
from byteps_tpu.parallel.mesh import EP_AXIS, make_mesh


def _cfg(**kw):
    cfg = moe.MoEConfig.tiny(vocab_size=64, seq=16)
    # fp32 + ample capacity: routing drops nothing, comparisons are exact
    kw.setdefault("capacity_factor", 8.0)
    return dataclasses.replace(cfg, dtype=jnp.float32, **kw)


def _layer0(params):
    """One layer's params (blocks are stacked on the leading [L] dim)."""
    return {k: v[0] for k, v in params["blocks"].items()}


def test_moe_layer_matches_per_token_oracle():
    cfg = _cfg()
    params = moe.init_params(jax.random.PRNGKey(0), cfg)
    p = _layer0(params)
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 8, cfg.dim),
                          jnp.float32)
    out, aux = moe.moe_layer(x, p, cfg)

    # oracle: every token goes through its top-k experts densely
    xf = np.asarray(x, np.float64).reshape(-1, cfg.dim)
    logits = xf @ np.asarray(p["router"], np.float64)
    probs = np.exp(logits - logits.max(-1, keepdims=True))
    probs /= probs.sum(-1, keepdims=True)
    expect = np.zeros_like(xf)
    for t in range(xf.shape[0]):
        top = np.argsort(-probs[t])[:cfg.top_k]
        gates = probs[t][top] / probs[t][top].sum()
        for g, e in zip(gates, top):
            h = xf[t]
            gate = h @ np.asarray(p["w_gate"][e], np.float64)
            up = h @ np.asarray(p["w_up"][e], np.float64)
            silu = gate / (1 + np.exp(-gate))
            expect[t] += g * ((silu * up) @ np.asarray(p["w_down"][e],
                                                      np.float64))
    np.testing.assert_allclose(
        np.asarray(out).reshape(-1, cfg.dim), expect, rtol=1e-4, atol=1e-5)
    assert np.isfinite(float(aux))


def test_moe_ep_matches_unsharded(devices):
    cfg = _cfg()
    params = moe.init_params(jax.random.PRNGKey(0), cfg)
    tokens = jnp.asarray(
        np.random.RandomState(2).randint(0, cfg.vocab_size, (8, 17)),
        jnp.int32)
    dense = moe.loss_fn(params, {"tokens": tokens}, cfg)

    mesh = make_mesh({EP_AXIS: 4}, devices[:4])
    specs = sh.moe_param_specs()

    def step(p, t):
        # tokens stay replicated over ep; experts are sharded -> the
        # all_to_all dispatch path runs, but the math must not change
        loss = moe.loss_fn(p, {"tokens": t}, cfg, ep_axis=EP_AXIS)
        return jax.lax.pmean(loss, EP_AXIS)

    f = shard_map(step, mesh=mesh, in_specs=(specs, P()), out_specs=P(),
                  check_vma=False)
    out = jax.jit(f)(params, tokens)
    np.testing.assert_allclose(np.asarray(out), np.asarray(dense),
                               rtol=1e-5, atol=1e-6)


def test_moe_ep_grads_flow(devices):
    """Gradients through the all_to_all dispatch are finite and the expert
    grads land sharded (each device only owns its experts' slices)."""
    cfg = _cfg()
    params = moe.init_params(jax.random.PRNGKey(0), cfg)
    tokens = jnp.asarray(
        np.random.RandomState(3).randint(0, cfg.vocab_size, (4, 17)),
        jnp.int32)
    mesh = make_mesh({EP_AXIS: 4}, devices[:4])
    specs = sh.moe_param_specs()

    def grads(p, t):
        # the ep training contract: grad the LOCAL loss, then
        # ep_grad_correction turns the per-device partials into the
        # global-mean gradient
        g = jax.grad(lambda q: moe.loss_fn(
            q, {"tokens": t}, cfg, ep_axis=EP_AXIS))(p)
        return moe.ep_grad_correction(g, EP_AXIS)

    f = shard_map(grads, mesh=mesh, in_specs=(specs, P()),
                  out_specs=specs, check_vma=False)
    g = jax.jit(f)(params, tokens)
    for leaf in jax.tree.leaves(g):
        assert np.all(np.isfinite(np.asarray(leaf)))
    # against the unsharded oracle
    g0 = jax.grad(lambda q: moe.loss_fn(q, {"tokens": tokens}, cfg))(params)
    np.testing.assert_allclose(
        np.asarray(g["blocks"]["w_down"]), np.asarray(g0["blocks"]["w_down"]),
        rtol=5e-4, atol=1e-6)


def test_moe_capacity_drops_tokens():
    """With a tight capacity, overflow tokens fall back to the residual
    (output contribution zero) instead of corrupting other slots."""
    cfg = _cfg(capacity_factor=0.1)
    params = moe.init_params(jax.random.PRNGKey(0), cfg)
    p = _layer0(params)
    x = jax.random.normal(jax.random.PRNGKey(4), (2, 8, cfg.dim),
                          jnp.float32)
    out, _ = moe.moe_layer(x, p, cfg)
    assert np.all(np.isfinite(np.asarray(out)))
    # capacity 1 per expert -> almost all tokens dropped -> tiny norm
    dense_out, _ = moe.moe_layer(
        x, p, dataclasses.replace(cfg, capacity_factor=8.0))
    assert (np.linalg.norm(np.asarray(out))
            < np.linalg.norm(np.asarray(dense_out)))
