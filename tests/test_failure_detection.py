"""Server-side failure detection (beyond the reference, which has none —
SURVEY.md §5.3): when every connection of a worker dies, the server fails
parked requests immediately so survivors error out in milliseconds instead
of wedging until their client timeout."""

import threading
import time

import numpy as np
import pytest

from byteps_tpu.config import Config
from byteps_tpu.server import run_server
from byteps_tpu.server.client import PSClient
from byteps_tpu.core.registry import TensorRegistry
from byteps_tpu.core.types import DataType

_PORT = [28100]


def _server(num_workers):
    port = _PORT[0]
    _PORT[0] += 1
    t = threading.Thread(
        target=run_server,
        args=(port, Config(num_workers=num_workers, num_servers=1)),
        daemon=True)
    t.start()
    return port, t


def _ctx(name, n, num_workers):
    reg = TensorRegistry(Config(num_workers=num_workers, num_servers=1))
    return reg.init_tensor(name, n * 4, DataType.FLOAT32)


def test_survivor_fails_fast_when_peer_dies(monkeypatch):
    """Worker A pushes and pulls (parks: B hasn't pushed); B disconnects
    without pushing; A's pull must error out well before the 60s client
    timeout."""
    monkeypatch.setenv("BYTEPS_CLIENT_TIMEOUT_S", "60")
    port, t = _server(2)
    addr = [f"127.0.0.1:{port}"]
    c0 = PSClient(addr, worker_id=0)
    c1 = PSClient(addr, worker_id=1)
    n = 1024
    ctx0 = _ctx("g", n, 2)
    ctx1 = _ctx("g", n, 2)
    x = np.ones(n, np.float32)

    result = {}

    def worker_a():
        t0 = time.monotonic()
        try:
            # init barrier inside push_pull; then PUSH; PULL parks on B
            c0.push_pull(ctx0, x.copy(), average=False, num_workers=2)
            result["outcome"] = "completed"
        except RuntimeError:
            result["outcome"] = "error"
        result["elapsed"] = time.monotonic() - t0

    th = threading.Thread(target=worker_a, daemon=True)
    th.start()
    c1.ensure_init(ctx1, n * 4)   # completes the init barrier with A
    time.sleep(1.0)               # A's pull is parked waiting on B's push
    c1.close(shutdown_servers=False)   # B vanishes (elastic/crash)
    th.join(timeout=30)
    assert not th.is_alive(), "survivor still wedged after peer death"
    assert result["outcome"] == "error"
    assert result["elapsed"] < 15, result   # ms-scale in practice, << 60s
    c0.close()
    t.join(timeout=10)


def test_round_rearms_after_departure(monkeypatch):
    """After a departure dropped a half-complete round, a fresh pair of
    workers (elastic resume) completes a new round correctly."""
    monkeypatch.setenv("BYTEPS_CLIENT_TIMEOUT_S", "60")
    port, t = _server(2)
    addr = [f"127.0.0.1:{port}"]
    n = 256
    c0 = PSClient(addr, worker_id=0)
    c1 = PSClient(addr, worker_id=1)
    ctx = _ctx("g", n, 2)
    x = np.full(n, 2.0, np.float32)

    fail = {}

    def worker_a():
        try:
            c0.push_pull(ctx, x.copy(), average=False, num_workers=2)
        except RuntimeError:
            fail["a"] = True

    th = threading.Thread(target=worker_a, daemon=True)
    th.start()
    c1.ensure_init(ctx, n * 4)            # complete the init barrier
    time.sleep(0.8)
    c1.close(shutdown_servers=False)      # kill the round
    th.join(timeout=30)
    assert fail.get("a"), "survivor should have errored"

    # elastic resume: worker 1 reconnects; a full round now works and the
    # dropped partial sum must NOT leak into the new aggregate
    c1b = PSClient(addr, worker_id=1)
    res = {}

    def w(c, tag):
        res[tag] = c.push_pull(ctx, x.copy(), average=False, num_workers=2)

    th0 = threading.Thread(target=w, args=(c0, "a"), daemon=True)
    th0.start()
    w(c1b, "b")
    th0.join(timeout=30)
    np.testing.assert_allclose(res["a"], 2 * x, rtol=1e-6)
    np.testing.assert_allclose(res["b"], 2 * x, rtol=1e-6)
    c0.close()
    c1b.close(shutdown_servers=False)
    t.join(timeout=10)


def test_clean_shutdown_is_not_a_departure(capfd):
    """Workers exiting via SHUTDOWN must not trigger departure handling
    (no spurious 'worker departed' on every normal multi-worker exit)."""
    port, t = _server(2)
    addr = [f"127.0.0.1:{port}"]
    c0 = PSClient(addr, worker_id=0)
    c1 = PSClient(addr, worker_id=1)
    n = 64
    ctx0 = _ctx("g", n, 2)
    ctx1 = _ctx("g", n, 2)
    x = np.ones(n, np.float32)
    res = {}

    def w(c, ctx, tag):
        res[tag] = c.push_pull(ctx, x.copy(), average=False, num_workers=2)

    th = threading.Thread(target=w, args=(c1, ctx1, "b"), daemon=True)
    th.start()
    w(c0, ctx0, "a")
    th.join(timeout=30)
    c0.close()                      # clean SHUTDOWN + close, staggered
    time.sleep(0.5)
    c1.close()
    t.join(timeout=10)
    err = capfd.readouterr().err
    assert "departed" not in err, err
