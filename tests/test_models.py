"""Model-zoo smoke + correctness tests (tiny configs, CPU mesh)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax

from byteps_tpu.core.state import get_state
from byteps_tpu.jax import distributed_optimizer
from byteps_tpu.jax.train import make_train_step
from byteps_tpu.models import bert, resnet, vgg


def test_bert_forward_and_mlm_loss(bps):
    cfg = bert.BertConfig.tiny(vocab_size=100, seq=32)
    params = bert.init_params(jax.random.PRNGKey(0), cfg)
    tokens = jnp.ones((2, 32), jnp.int32)
    hidden = bert.forward(params, tokens, cfg)
    assert hidden.shape == (2, 32, cfg.dim)
    labels = jnp.where(jnp.arange(32)[None, :] % 7 == 0, tokens, -100)
    loss = bert.loss_fn(params, {"tokens": tokens, "labels": labels}, cfg)
    assert np.isfinite(float(loss))


def test_bert_trains(bps):
    mesh = get_state().mesh
    cfg = bert.BertConfig.tiny(vocab_size=50, seq=16)
    # fp32 at tiny scale for a stable loss-decrease signal
    cfg = bert.BertConfig(vocab_size=50, dim=64, n_layers=2, n_heads=4,
                          ffn_dim=128, max_seq_len=16, remat=False,
                          dtype=jnp.float32)
    params = bert.init_params(jax.random.PRNGKey(0), cfg)
    tx = distributed_optimizer(optax.adam(1e-3))
    step = make_train_step(lambda p, b: bert.loss_fn(p, b, cfg), tx, mesh)
    opt_state = tx.init(params)

    rng = np.random.RandomState(0)
    tokens = rng.randint(0, 50, size=(16, 16)).astype(np.int32)
    labels = np.where(rng.rand(16, 16) < 0.15, tokens, -100).astype(np.int32)
    batch = {"tokens": tokens, "labels": labels}
    losses = []
    for _ in range(15):
        params, opt_state, loss = step(params, opt_state, batch)
        losses.append(float(loss))
    assert losses[-1] < losses[0], losses


def test_resnet_forward_shapes(bps):
    cfg = resnet.ResNetConfig.tiny()
    params, state = resnet.init_params(jax.random.PRNGKey(0), cfg)
    x = jnp.ones((2, 32, 32, 3), jnp.float32)
    logits, new_state = resnet.forward(params, state, x, cfg, train=True)
    assert logits.shape == (2, 10)
    assert np.all(np.isfinite(np.asarray(logits)))
    # eval mode uses running stats and leaves state alone
    logits_eval, st2 = resnet.forward(params, state, x, cfg, train=False)
    assert logits_eval.shape == (2, 10)


def test_resnet_trains(bps):
    mesh = get_state().mesh
    cfg = resnet.ResNetConfig.tiny(n_classes=4)
    params, bn_state = resnet.init_params(jax.random.PRNGKey(0), cfg)
    tx = distributed_optimizer(optax.sgd(0.05))

    def loss_with_aux(p, b):
        # bn_state is threaded through as an aux output; for this test the
        # batch-stat path suffices so we drop new_state in the loss
        loss, _ = resnet.loss_fn(p, bn_state, b, cfg)
        return loss

    step = make_train_step(loss_with_aux, tx, mesh)
    opt_state = tx.init(params)
    rng = np.random.RandomState(0)
    x = rng.randn(16, 32, 32, 3).astype(np.float32)
    y = rng.randint(0, 4, size=(16,)).astype(np.int32)
    batch = {"x": x, "y": y}
    losses = []
    for _ in range(10):
        params, opt_state, loss = step(params, opt_state, batch)
        losses.append(float(loss))
    assert losses[-1] < losses[0], losses


def test_vgg_forward_shapes(bps):
    cfg = vgg.VGGConfig.tiny()
    params = vgg.init_params(jax.random.PRNGKey(0), cfg)
    x = jnp.ones((2, 32, 32, 3), jnp.float32)
    logits = vgg.forward(params, x, cfg)
    assert logits.shape == (2, 10)
    assert np.all(np.isfinite(np.asarray(logits)))
    # full vgg16 plan builds with the documented 138M-parameter count
    full = vgg.init_params(jax.random.PRNGKey(0), vgg.VGGConfig.vgg16())
    assert abs(vgg.param_count(full) - 138_357_544) < 1_000_000


def test_vgg_trains(bps):
    mesh = get_state().mesh
    cfg = vgg.VGGConfig.tiny(n_classes=4)
    # fp32 at tiny scale for a stable loss-decrease signal
    cfg = vgg.VGGConfig(plan=cfg.plan, fc_width=cfg.fc_width, n_classes=4,
                        image_size=32, dtype=jnp.float32)
    params = vgg.init_params(jax.random.PRNGKey(0), cfg)
    tx = distributed_optimizer(optax.sgd(0.01))
    step = make_train_step(lambda p, b: vgg.loss_fn(p, b, cfg), tx, mesh)
    opt_state = tx.init(params)
    rng = np.random.RandomState(0)
    x = rng.randn(16, 32, 32, 3).astype(np.float32)
    y = rng.randint(0, 4, size=(16,)).astype(np.int32)
    batch = {"x": x, "y": y}
    losses = []
    for _ in range(10):
        params, opt_state, loss = step(params, opt_state, batch)
        losses.append(float(loss))
    assert losses[-1] < losses[0], losses


def test_llama_chunked_xent_matches_dense(bps):
    """cfg.xent_chunks (the chunked-vocab loss that never materializes
    [B,S,V]) must agree with the dense logsumexp loss in value AND
    gradient — it is the same math under a different checkpoint/fusion
    schedule."""
    import dataclasses

    from byteps_tpu.models import llama

    cfg = llama.LlamaConfig.tiny(vocab_size=64, seq=16)
    cfg_f32 = dataclasses.replace(cfg, dtype=jnp.float32)
    params = llama.init_params(jax.random.PRNGKey(1), cfg_f32)
    tokens = jnp.asarray(
        np.random.RandomState(0).randint(0, 64, (2, 17)), jnp.int32)

    dense = jax.value_and_grad(
        lambda p: llama.loss_fn(p, {"tokens": tokens}, cfg_f32))
    cfg_ck = dataclasses.replace(cfg_f32, xent_chunks=4)
    chunk = jax.value_and_grad(
        lambda p: llama.loss_fn(p, {"tokens": tokens}, cfg_ck))

    l0, g0 = dense(params)
    l1, g1 = chunk(params)
    np.testing.assert_allclose(float(l0), float(l1), rtol=1e-6)
    for a, b in zip(jax.tree_util.tree_leaves(g0),
                    jax.tree_util.tree_leaves(g1)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)
    # non-divisible vocab falls back to the dense path, silently correct
    cfg_bad = dataclasses.replace(cfg_f32, xent_chunks=7)
    l2 = llama.loss_fn(params, {"tokens": tokens}, cfg_bad)
    np.testing.assert_allclose(float(l2), float(l0), rtol=1e-6)
