"""Test harness: run everything on a virtual 8-device CPU mesh.

The reference tests all roles on one machine over loopback with
BYTEPS_FORCE_DISTRIBUTED (reference: tests/meta_test.py:27-58). The JAX
analogue: force the CPU platform with 8 virtual devices so every mesh/
collective path is exercised without TPU hardware. Env must be set before
jax initializes its backends, hence module scope here.
"""

import os
import tempfile

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    flags = (flags + " --xla_force_host_platform_device_count=8").strip()
if "xla_cpu_enable_fast_math" not in flags:
    # XLA CPU fast-math reassociates FMA contraction per SHAPE, so the
    # same elementwise math on a (50,7) leaf vs its flat 1/N shards can
    # differ by 1 ULP — which would make the locality-shard parity
    # suites (shard on vs off bitwise) flake on exactly the property
    # they guard. TPU codegen has no fast-math reassociation; pinning
    # it off here makes the CPU harness match the hardware contract.
    flags = (flags + " --xla_cpu_enable_fast_math=false").strip()
os.environ["XLA_FLAGS"] = flags
os.environ.setdefault("BYTEPS_LOG_LEVEL", "WARNING")
# flight-recorder dumps (fatal wire errors fire them automatically)
# land in a temp dir, not the checkout — tests that assert on the dump
# path override this themselves
os.environ.setdefault(
    "BYTEPS_FLIGHT_DIR",
    os.path.join(tempfile.gettempdir(), f"bps-flight-{os.getpid()}"))

import jax  # noqa: E402
import pytest  # noqa: E402

# Force CPU even when the outer environment pre-imported jax against a TPU
# platform (env vars are latched at jax import time, so config.update is the
# only reliable override).
jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 8)
except AttributeError:  # pre-0.5 jax: XLA_FLAGS above already forced 8
    pass

from byteps_tpu.utils import jax_compat  # noqa: E402

jax_compat.ensure()


@pytest.fixture(scope="session")
def devices():
    devs = jax.devices()
    assert len(devs) == 8, f"expected 8 virtual devices, got {len(devs)}"
    return devs


@pytest.fixture()
def bps():
    """Fresh byteps_tpu init/shutdown around each test."""
    import byteps_tpu as bps_mod
    from byteps_tpu.core.state import GlobalState

    GlobalState._instance = None  # reset singleton between tests
    bps_mod.init()
    yield bps_mod
    bps_mod.shutdown()
    GlobalState._instance = None
