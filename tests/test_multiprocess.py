"""Multi-process runtime tests: real OS processes, the reference's MetaTest
shape (tests/meta_test.py:27-86 — all roles on one machine over loopback)
upgraded to the JAX world:

- global-mesh mode: 2 processes x 4 virtual CPU chips rendezvous through
  jax.distributed (the scheduler-rendezvous analogue, global.cc:283-297)
  and build ONE 8-device mesh; push_pull is an XLA collective over the
  gloo/DCN transport.
- PS mode: 2 worker processes each keep a LOCAL 4-device mesh and sum
  across processes through the DCN PS — the reference's NCCL-intra +
  ps-lite-inter split (docs/architecture.md "General Workflow").
- launcher MetaTest: server + worker as separate OS processes spawned via
  the launcher (bpslaunch analogue), exercising fork/env/socket lifecycle.

Subprocesses configure their own jax (4 CPU devices each) — the parent's
conftest does not apply to them.
"""

import os
import subprocess
import sys
import time

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# distinct port blocks per pytest run; each test uses its own sub-block
_PORT_BASE = 21000 + (os.getpid() % 1000)


def _spawn_one(code: str, env: dict):
    """Spawn `code` in a fresh interpreter with a clean jax environment."""
    e = {**os.environ,
         # wedges (e.g. a stale server from a crashed run holding the
         # port) must fail fast, not eat the subprocess timeout
         "BYTEPS_CLIENT_TIMEOUT_S": "120",
         **env,
         "PYTHONPATH": REPO + os.pathsep + os.environ.get("PYTHONPATH", "")}
    # the parent conftest's XLA_FLAGS would force 8 devices; drop it
    e.pop("XLA_FLAGS", None)
    e.pop("JAX_PLATFORMS", None)
    return subprocess.Popen(
        [sys.executable, "-c", code], env=e, cwd=REPO,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)


def _finish(procs, timeout=420):  # generous: cold XLA/gloo compile is slow
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=timeout)
        except subprocess.TimeoutExpired:
            p.kill()
            out, _ = p.communicate()
            out += "\n<TIMEOUT>"
        outs.append(out)
    return outs


def _reap(*procs):
    """Kill any still-running subprocess (failure-path cleanup: a leaked
    server keeps LISTENing on its port and can wedge later runs)."""
    for p in procs:
        if p is not None and p.poll() is None:
            p.kill()
            try:
                p.communicate(timeout=10)
            except Exception:
                pass


_GLOBAL_MESH = r"""
import os
from byteps_tpu.utils.jax_compat import force_cpu
force_cpu(4)
import jax
import numpy as np
import byteps_tpu as bps

pid = int(os.environ["PROC_ID"])
bps.init()
assert jax.process_count() == 2, jax.process_count()
assert bps.size() == 2 and bps.rank() == pid, (bps.size(), bps.rank())
from byteps_tpu.core.state import get_state
mesh = get_state().mesh
assert mesh.devices.size == 8, mesh  # global mesh spans both processes

# each process contributes (pid+1) on its 4 local devices: the 8-device
# sum is 4*1 + 4*2 = 12
x = np.full((4, 16), float(pid + 1), np.float32)
out = np.asarray(bps.push_pull(x, stacked=True, average=False, name="g"))
assert np.allclose(out, 12.0), out[:3]
out = np.asarray(bps.push_pull(x, stacked=True, average=True, name="g"))
assert np.allclose(out, 1.5), out[:3]
bps.shutdown()
print("GLOBAL_MESH_OK", pid)
"""


def test_global_mesh_two_processes():
    coord = _PORT_BASE + 100
    procs = [_spawn_one(_GLOBAL_MESH, {
        "BYTEPS_NUM_PROCESS": "2",
        "BYTEPS_PROCESS_ID": str(i),
        "BYTEPS_COORD_PORT": str(coord),
        "PROC_ID": str(i),
    }) for i in range(2)]
    try:
        outs = _finish(procs)
        if any("Multiprocess computations aren't implemented on the CPU "
               "backend" in o for o in outs):
            # capability skip, not an xfail: this jaxlib's CPU backend
            # refuses to COMPILE cross-process collectives (the XLA:CPU
            # runtime has no inter-process transfer layer), so global-mesh
            # mode is unrunnable here by construction. Any other failure
            # mode still fails the test — the skip keys on the exact
            # backend error string.
            pytest.skip(
                "jaxlib CPU backend cannot compile multi-process "
                "collectives (XlaRuntimeError: 'Multiprocess computations "
                "aren't implemented on the CPU backend'); global-mesh "
                "mode needs an accelerator or a jaxlib with CPU "
                "cross-process collective support")
        for i, (p, out) in enumerate(zip(procs, outs)):
            assert p.returncode == 0, f"proc {i} failed:\n{out[-3000:]}"
            assert f"GLOBAL_MESH_OK {i}" in out, out[-2000:]
    finally:
        _reap(*procs)


_PS_WORKER = r"""
import os
from byteps_tpu.utils.jax_compat import force_cpu
force_cpu(4)
import jax
import numpy as np
import byteps_tpu as bps

pid = int(os.environ["PROC_ID"])
bps.init()
assert jax.process_count() == 2
from byteps_tpu.core.state import get_state
st = get_state()
assert st.mesh.devices.size == 4, st.mesh   # LOCAL mesh (PS mode)
assert st.ps_client is not None

# local ICI sum = 4*(pid+1); PS sums across the 2 workers -> 12
x = np.full((4, 8), float(pid + 1), np.float32)
out = np.asarray(bps.push_pull(x, stacked=True, average=False, name="g"))
assert np.allclose(out, 12.0), out[:3]

# a 3-round training-loop shape: both workers stay consistent
w = np.zeros(8, np.float32)
for step in range(3):
    g = np.full((4, 8), float(pid + 1 + step), np.float32)
    gsum = np.asarray(bps.push_pull(g, stacked=True, average=False,
                                    name="grad/w"))
    w -= 0.1 * gsum
print("W_DIGEST", pid, float(w.sum()))
bps.shutdown()
print("PS_WORKER_OK", pid)
"""


def test_ps_mode_two_processes():
    ps_port = _PORT_BASE + 200
    coord = _PORT_BASE + 210
    srv_env = {**os.environ,
               "DMLC_NUM_WORKER": "2", "DMLC_NUM_SERVER": "1",
               "DMLC_PS_ROOT_PORT": str(ps_port), "JAX_PLATFORMS": "cpu",
               "PYTHONPATH": REPO + os.pathsep + os.environ.get("PYTHONPATH", "")}
    srv = subprocess.Popen([sys.executable, "-m", "byteps_tpu.server"],
                           env=srv_env, cwd=REPO, stdout=subprocess.PIPE,
                           stderr=subprocess.STDOUT, text=True)
    time.sleep(1.0)
    workers = []
    try:
        workers = [_spawn_one(_PS_WORKER, {
            "BYTEPS_NUM_PROCESS": "2", "BYTEPS_PROCESS_ID": str(i),
            "BYTEPS_COORD_PORT": str(coord),
            "DMLC_NUM_WORKER": "2", "DMLC_NUM_SERVER": "1",
            "DMLC_WORKER_ID": str(i),
            "DMLC_PS_ROOT_PORT": str(ps_port),
            "BYTEPS_FORCE_DISTRIBUTED": "1",
            "PROC_ID": str(i),
        }) for i in range(2)]
        outs = _finish(workers)
        digests = {}
        for i, (p, out) in enumerate(zip(workers, outs)):
            assert p.returncode == 0, f"worker {i} failed:\n{out[-3000:]}"
            assert f"PS_WORKER_OK {i}" in out, out[-2000:]
            for line in out.splitlines():
                if line.startswith("W_DIGEST"):
                    digests[i] = float(line.split()[2])
        assert digests[0] == digests[1], digests  # weights stayed consistent
        srv.wait(timeout=20)
        assert srv.returncode == 0
    finally:
        _reap(srv, *workers)


_LAUNCH_TRAIN = (
    # config.update, not env: the axon plugin otherwise initializes (and,
    # with a wedged tunnel, hangs) regardless of JAX_PLATFORMS
    "import jax;"
    "jax.config.update('jax_platforms', 'cpu');"
    "import numpy as np, byteps_tpu as bps;"
    "bps.init();"
    "x = np.arange(16, dtype=np.float32);"
    "out = np.asarray(bps.push_pull(x, name='t', average=False));"
    "assert out.shape == (16,), out.shape;"
    "bps.shutdown();"
    "print('LAUNCH_WORKER_OK')"
)


def test_launcher_metatest_roles():
    """The reference MetaTest shape via the launcher: server role + worker
    role as real OS processes over loopback (launch.py:241-249 analogue)."""
    port = _PORT_BASE + 300
    common = {"DMLC_NUM_WORKER": "1", "DMLC_NUM_SERVER": "1",
              "DMLC_PS_ROOT_PORT": str(port), "JAX_PLATFORMS": "cpu",
              "BYTEPS_CLIENT_TIMEOUT_S": "120",
              "PYTHONPATH": REPO + os.pathsep + os.environ.get("PYTHONPATH", "")}
    srv = subprocess.Popen(
        [sys.executable, "-m", "byteps_tpu.launcher"],
        env={**os.environ, **common, "DMLC_ROLE": "server"},
        cwd=REPO, stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
    try:
        time.sleep(1.0)
        wrk = subprocess.run(
            [sys.executable, "-m", "byteps_tpu.launcher",
             sys.executable, "-c", _LAUNCH_TRAIN],
            env={**os.environ, **common, "DMLC_ROLE": "worker",
                 "BYTEPS_FORCE_DISTRIBUTED": "1"},
            cwd=REPO, capture_output=True, text=True, timeout=420)
        assert wrk.returncode == 0, wrk.stdout[-2000:] + wrk.stderr[-2000:]
        assert "LAUNCH_WORKER_OK" in wrk.stdout
        out, _ = srv.communicate(timeout=30)
        assert srv.returncode == 0, out[-2000:]
    finally:
        _reap(srv)
