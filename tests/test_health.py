"""Training-health plane tests (core/health.py + the native in-fold
statistics pass, docs/observability.md "Training-health plane").

Pins the PR's acceptance surface:

- the in-fold statistics are BITWISE-neutral: aggregates with
  BYTEPS_HEALTH on vs off compare equal as raw bits across dense f32
  (fused last-fold kernel), bf16, rowsparse and fused-PUSHPULL traffic;
- the statistics themselves are correct (sum-of-squares / abs-max over
  FINITE elements, NaN/Inf counted) on both the publish-scan and the
  fused multi-worker path, served by the HEALTH_PULL wire op and the
  in-process ``server.key_health`` mirror;
- the detector is a pure clockless hysteresis machine: two stacks fed
  identical signals emit identical verdicts (incl. the fidelity-drift →
  codec de-escalation chain), warmup never fires, cooldowns don't flap;
- injected-NaN chaos (BYTEPS_CHAOS_NAN_LEAF) shows detect →
  flight-event → (guard on) bounded fail-fast with "flight record
  dumped", and guard-off training continues with
  ``health/nonfinite_rounds`` counting;
- ci/perf_gate.py reads the new archive keys with the right
  directionality (grad_norm skipped, nonfinite_leaves lower-is-better).
"""

import contextlib
import importlib.util
import os
import threading

import numpy as np
import pytest

from byteps_tpu.config import Config
from byteps_tpu.core.codec_plane import CodecController, CodecPlan, \
    RoundSignal
from byteps_tpu.core.health import HealthDetector, HealthSignal
from byteps_tpu.core.metrics import StepReport, classify_step
from byteps_tpu.core.registry import TensorRegistry
from byteps_tpu.core.types import DataType, RequestType, get_command_type
from byteps_tpu.server import (
    _STAT_SLOTS, key_health, native_stat_slot_names, run_server,
)
from byteps_tpu.server.client import PSClient

CMD_F32 = get_command_type(RequestType.DEFAULT_PUSH_PULL,
                           DataType.FLOAT32)
CMD_BF16 = get_command_type(RequestType.DEFAULT_PUSH_PULL,
                            DataType.BFLOAT16)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_PORT = [21370]


def _start_server(num_workers: int, health: bool, monkeypatch):
    """One loopback server with BYTEPS_HEALTH latched at construction
    (the native pass reads the env per Server instance). Returns its
    address; connecting a client proves construction finished, so the
    caller may flip the env afterwards for the next server."""
    port = _PORT[0]
    _PORT[0] += 1
    monkeypatch.setenv("BYTEPS_HEALTH", "1" if health else "0")
    cfg = Config(num_workers=num_workers, num_servers=1)
    t = threading.Thread(target=run_server, args=(port, cfg),
                         daemon=True)
    t.start()
    return f"127.0.0.1:{port}", t


# --------------------------------------------------------------------- #
# detector unit (pure hysteresis machine)
# --------------------------------------------------------------------- #


def _sig(step, gn=None, nf=0, drift=None):
    return HealthSignal(step=step, grad_norm=gn, nonfinite_leaves=nf,
                        fidelity_drift=drift)


def test_detector_nonfinite_fires_every_round():
    d = HealthDetector()
    assert d.observe(_sig(1, gn=1.0, nf=2)) == ("nonfinite",)
    assert d.observe(_sig(2, gn=1.0, nf=1)) == ("nonfinite",)
    assert d.observe(_sig(3, gn=1.0)) == ()


def test_detector_warmup_never_fires():
    d = HealthDetector(streak=1)
    # fewer than 4 trailing samples: no baseline, no explode/collapse
    for s in range(3):
        assert d.observe(_sig(s, gn=10.0 ** s)) == ()


def test_detector_explosion_streak_and_cooldown():
    d = HealthDetector(window=16, explode_ratio=10.0, streak=2,
                       cooldown=3)
    for s in range(6):
        assert d.observe(_sig(s, gn=1.0)) == ()
    # first crossing clocks the streak, second fires
    assert d.observe(_sig(6, gn=50.0)) == ()
    assert d.observe(_sig(7, gn=50.0)) == ("explode",)
    # cooldown: the still-exploded rounds stay silent, then re-fire
    fired = [d.observe(_sig(8 + i, gn=50.0)) for i in range(8)]
    assert ("explode",) in fired
    assert fired.count(("explode",)) <= 2  # no per-round flapping


def test_detector_collapse():
    d = HealthDetector(window=8, collapse_ratio=0.01, streak=2)
    for s in range(6):
        assert d.observe(_sig(s, gn=1.0)) == ()
    assert d.observe(_sig(6, gn=1e-5)) == ()
    assert d.observe(_sig(7, gn=1e-5)) == ("collapse",)


def test_detector_drift():
    d = HealthDetector(drift_frac=0.1, streak=2)
    assert d.observe(_sig(1, gn=1.0, drift=0.5)) == ()
    assert d.observe(_sig(2, gn=1.0, drift=0.5)) == ("drift",)
    # below threshold resets the streak
    assert d.observe(_sig(3, gn=1.0, drift=0.01)) == ()


def test_detector_nonfinite_rounds_never_enter_window():
    """A poisoned round's (meaningless) norm must not inflate the
    trailing median — the next honest explosion still fires."""
    d = HealthDetector(window=8, explode_ratio=10.0, streak=1,
                       cooldown=0)
    for s in range(6):
        d.observe(_sig(s, gn=1.0))
    assert d.observe(_sig(6, gn=1000.0, nf=3)) == ("nonfinite",)
    # had 1000.0 entered the window the median would still be 1.0, but
    # a few more poisoned rounds would shift it — pin directly:
    assert 1000.0 not in d._norms
    assert d.observe(_sig(7, gn=15.0)) == ("explode",)


def test_detector_two_stack_determinism():
    """Identical signal sequences -> identical verdict sequences (the
    aggregation-safety property the codec veto rests on)."""
    seq = []
    rng = np.random.RandomState(7)
    for s in range(60):
        gn = float(abs(rng.randn())) + 0.5
        if s in (20, 21, 22):
            gn *= 100.0
        nf = 1 if s == 35 else 0
        drift = 0.4 if s in (45, 46) else 0.0
        seq.append(_sig(s, gn=gn, nf=nf, drift=drift))
    a = HealthDetector(streak=2, cooldown=4)
    b = HealthDetector(streak=2, cooldown=4)
    va = [a.observe(s) for s in seq]
    vb = [b.observe(s) for s in seq]
    assert va == vb
    assert any(v for v in va)  # the sequence exercised real firings


# --------------------------------------------------------------------- #
# native in-fold statistics + HEALTH_PULL
# --------------------------------------------------------------------- #


def test_infold_stats_single_worker_scan(monkeypatch):
    """Single-worker dense round: the adopt path publishes via the
    read-only scan; sumsq/absmax cover finite elements only and the
    NaN is COUNTED, not folded into the norm."""
    addr, _ = _start_server(1, health=True, monkeypatch=monkeypatch)
    c = PSClient([addr], worker_id=0)
    x = np.zeros(100, np.float32)
    x[0], x[1], x[2] = 3.0, -4.0, np.nan
    c.init_key(0, 7, np.zeros_like(x), CMD_F32)
    c.zpush(0, 7, x, CMD_F32)
    out = np.empty_like(x)
    c.zpull(0, 7, out, CMD_F32)
    rec = key_health(7)
    assert rec is not None
    assert rec["round"] == 1 and rec["elems"] == 100
    assert rec["sumsq"] == pytest.approx(25.0)
    assert rec["absmax"] == pytest.approx(4.0)
    assert rec["nonfinite"] == 1
    # wire surface agrees with the in-process mirror
    wrec = c.health_pull(0, 7)
    assert wrec == rec
    # unknown key: None, never a zeroed record
    assert c.health_pull(0, 999) is None
    c.close()


def _init2(w0, w1, key, z, cmd):
    """Two-worker init: the init reply is withheld until BOTH workers'
    init pushes arrive (global barrier), so the calls must overlap."""
    t = threading.Thread(target=w0.init_key, args=(0, key, z, cmd),
                         daemon=True)
    t.start()
    w1.init_key(0, key, z, cmd)
    t.join(timeout=30)
    assert not t.is_alive()


def test_infold_stats_fused_multiworker(monkeypatch):
    """Two-worker dense round: the LAST fold runs the fused stat
    kernel — statistics describe the post-aggregation sum."""
    addr, _ = _start_server(2, health=True, monkeypatch=monkeypatch)
    c0 = PSClient([addr], worker_id=0)
    c1 = PSClient([addr], worker_id=1)
    rng = np.random.RandomState(0)
    a = rng.randn(4097).astype(np.float32)
    b = rng.randn(4097).astype(np.float32)
    z = np.zeros_like(a)
    _init2(c0, c1, 11, z, CMD_F32)
    c0.zpush(0, 11, a, CMD_F32)
    c1.zpush(0, 11, b, CMD_F32)
    out = np.empty_like(a)
    c0.zpull(0, 11, out, CMD_F32)
    agg = a + b
    np.testing.assert_array_equal(out, agg)
    rec = key_health(11)
    assert rec is not None and rec["nonfinite"] == 0
    assert rec["elems"] == 4097
    assert rec["sumsq"] == pytest.approx(
        float(np.dot(agg.astype(np.float64), agg.astype(np.float64))),
        rel=1e-10)
    assert rec["absmax"] == pytest.approx(
        float(np.abs(agg).max()), rel=1e-7)
    c0.close()
    c1.close()


def test_key_health_none_when_off(monkeypatch):
    addr, _ = _start_server(1, health=False, monkeypatch=monkeypatch)
    c = PSClient([addr], worker_id=0)
    x = np.ones(32, np.float32)
    c.init_key(0, 5, np.zeros_like(x), CMD_F32)
    c.zpush(0, 5, x, CMD_F32)
    out = np.empty_like(x)
    c.zpull(0, 5, out, CMD_F32)
    assert key_health(5) is None
    assert c.health_pull(0, 5) is None
    c.close()


def test_stat_slots_appended():
    names = native_stat_slot_names()
    assert names == list(_STAT_SLOTS)
    assert names[-9:] == ["tx_batches", "tx_msgs", "rx_batches",
                          "rx_msgs", "stripe_segs", "stripe_bytes",
                          "fused_decode_folds", "reg_blocks",
                          "reg_miss"]
    assert names[-13:-9] == ["health_rounds", "health_nonfinite",
                             "window_deferred", "window_rejected"]


def _bf16(x: np.ndarray) -> np.ndarray:
    return (np.ascontiguousarray(x, np.float32).view(np.uint32)
            >> 16).astype(np.uint16)


def test_aggregate_parity_health_on_off(monkeypatch):
    """BITWISE-neutrality: identical traffic against a health-on and a
    health-off server publishes identical aggregates — dense f32
    (multi-worker: the fused stat kernel wrote the bits), bf16
    (publish scan), rowsparse, and fused PUSHPULL — NaN/Inf payload
    lanes included (uint comparisons)."""
    addr_on, _ = _start_server(2, health=True, monkeypatch=monkeypatch)
    con0 = PSClient([addr_on], worker_id=0)  # proves server A built
    addr_off, _ = _start_server(2, health=False,
                                monkeypatch=monkeypatch)
    con1 = PSClient([addr_on], worker_id=1)
    coff0 = PSClient([addr_off], worker_id=0)
    coff1 = PSClient([addr_off], worker_id=1)
    rng = np.random.RandomState(3)

    def dense_round(key, cmd, a, b, view):
        outs = []
        for w0, w1 in ((con0, con1), (coff0, coff1)):
            z = np.zeros_like(a)
            _init2(w0, w1, key, z, cmd)
            w0.zpush(0, key, a, cmd)
            w1.zpush(0, key, b, cmd)
            out = np.empty_like(a)
            w0.zpull(0, key, out, cmd)
            outs.append(out.view(view))
        np.testing.assert_array_equal(outs[0], outs[1])

    # dense f32 with special lanes (NaN/Inf/subnormal)
    a = rng.randn(1025).astype(np.float32)
    b = rng.randn(1025).astype(np.float32)
    a[0], a[1], a[2] = np.nan, np.inf, np.float32(1e-42)
    dense_round(100, CMD_F32, a, b, np.uint32)
    # bf16 (widen-fold-narrow; publish scan on the health server)
    dense_round(101, CMD_BF16, _bf16(rng.randn(513) * 8),
                _bf16(rng.randn(513) * 8), np.uint16)
    # fused PUSHPULL: reply IS the aggregate
    fouts = []
    fpay = [rng.randn(256).astype(np.float32) for _ in range(2)]
    for w0, w1 in ((con0, con1), (coff0, coff1)):
        z = np.zeros(256, np.float32)
        _init2(w0, w1, 102, z, CMD_F32)
        res = {}
        evs = []
        for wi, w in enumerate((w0, w1)):
            out = np.empty(256 * 4, np.uint8)
            ev = threading.Event()
            w.zpushpull_async(
                0, 102, fpay[wi], out, CMD_F32,
                (lambda n, err, o=out, i=wi, e=ev:
                 (res.__setitem__(i, bytes(o)), e.set())),
                epoch=(1 << 16))
            evs.append(ev)
        for ev in evs:
            assert ev.wait(60)
        fouts.append(res[0])
    assert fouts[0] == fouts[1]
    # rowsparse: scatter-add rows, dense publish scan
    souts = []
    g = np.zeros((64, 8), np.float32)
    g[3] = rng.randn(8)
    g[40] = rng.randn(8)
    for tag, w0, w1 in (("on", con0, con1), ("off", coff0, coff1)):
        reg = TensorRegistry(Config(num_workers=2, num_servers=1))
        ctx = reg.init_tensor(f"emb-{tag}", 64 * 8 * 4,
                              DataType.FLOAT32, align_bytes=32)
        zt = np.zeros(64 * 8, np.float32)
        it = threading.Thread(target=w0.init_tensor, args=(ctx, zt),
                              daemon=True)
        it.start()
        w1.init_tensor(ctx, zt)
        it.join(timeout=30)
        assert not it.is_alive()
        r = {}
        ths = [threading.Thread(
            target=lambda w=w, i=i: r.__setitem__(
                i, w.push_pull_rowsparse(ctx, g, average=False,
                                         num_workers=2)))
            for i, w in enumerate((w0, w1))]
        for t in ths:
            t.start()
        for t in ths:
            t.join(timeout=60)
        souts.append(r[0].view(np.uint32).copy())
    np.testing.assert_array_equal(souts[0], souts[1])
    # the health-on server actually took statistics on this traffic
    rec = key_health(100)
    assert rec is not None and rec["nonfinite"] >= 1  # the NaN/Inf lanes
    for c in (con0, con1, coff0, coff1):
        c.close()


# --------------------------------------------------------------------- #
# codec-plane numerics veto (deterministic, two-stack)
# --------------------------------------------------------------------- #


def _perf(step, pull=50.0, compute=5.0, degraded=False):
    return RoundSignal(step=step, compute_ms=compute, pull_ms=pull,
                       degraded=degraded)


def test_controller_veto_blocks_escalation():
    c = CodecController(ladder=("dense", "lossless", "onebit"),
                        up_rounds=1, pull_ratio=1.0)
    plan = CodecPlan()
    # degraded rounds can never escalate, however PULL-bound
    for s in range(5):
        assert c.decide(plan, _perf(s, degraded=True)) is None
    assert plan.rung == 0
    # healthy pressure escalates as before
    assert c.decide(plan, _perf(6)) == "lossless"


def test_controller_veto_forces_deescalation_to_safe_rung():
    c = CodecController(ladder=("dense", "lossless", "onebit"),
                        up_rounds=1, pull_ratio=1.0)
    plan = CodecPlan(rung=2)  # on the lossy rung
    assert c.decide(plan, _perf(1, degraded=True)) == "lossless"
    assert plan.rung == 1
    # already safe: hold (no further forced move, no escalation)
    assert c.decide(plan, _perf(2, degraded=True)) is None
    assert plan.rung == 1


def test_controller_veto_jumps_to_dense_without_lossless():
    c = CodecController(ladder=("dense", "onebit"), up_rounds=1,
                        pull_ratio=1.0)
    plan = CodecPlan(rung=1)
    assert c.decide(plan, _perf(1, degraded=True)) == "dense"
    assert plan.rung == 0


def test_controller_veto_all_lossy_ladder_holds():
    """An all-lossy ladder has no numerics-safe rung: the veto blocks
    escalation but must NOT re-return the same tier every degraded
    round (switch-per-round spam with no effect)."""
    c = CodecController(ladder=("onebit", "randomk"), up_rounds=1,
                        pull_ratio=1.0)
    plan = CodecPlan(rung=1)
    for s in range(4):
        assert c.decide(plan, _perf(s, degraded=True)) is None
    assert plan.rung == 1  # held, never thrashed


def test_health_plane_refuses_to_arm_without_metrics():
    """BYTEPS_HEALTH=1 with BYTEPS_METRICS=0 would be per-step cost
    with the detector (and NaN guard) never running — the plane must
    refuse to arm rather than silently degrade."""
    from byteps_tpu.core.health import HealthPlane
    from byteps_tpu.core.metrics import MetricsRegistry
    cfg = Config(num_workers=1, num_servers=0, health=True,
                 metrics_on=False)
    plane = HealthPlane(cfg, MetricsRegistry(enabled=False))
    assert plane.enabled is False
    assert plane.begin_collect(4) is None


def test_drift_to_deescalation_two_stack():
    """The acceptance chain, two independent stacks: fidelity-drift
    signals -> detector verdict -> degraded RoundSignal -> controller
    de-escalates off the lossy rung — identical on both stacks, and
    pinned to land on ``lossless``."""
    def run_stack():
        det = HealthDetector(streak=2, cooldown=4)
        ctl = CodecController(ladder=("dense", "lossless", "onebit"),
                              up_rounds=1, pull_ratio=1.0)
        plan = CodecPlan(rung=2)
        out = []
        for s in range(10):
            drift = 0.5 if s >= 4 else 0.0
            flags = det.observe(_sig(s, gn=1.0, drift=drift))
            tier = ctl.decide(plan, _perf(s, degraded=bool(flags)))
            out.append((flags, tier, plan.rung))
        return out
    a, b = run_stack(), run_stack()
    assert a == b
    # the drift verdict fired and forced the plan off onebit
    assert any(f == ("drift",) for f, _, _ in a)
    assert ("drift",) in [f for f, t, _ in a if t == "lossless"] \
        or any(t == "lossless" for _, t, _ in a)
    assert a[-1][2] == 1  # parked on the numerics-safe lossless rung


def test_round_signal_degraded_from_report():
    r = StepReport(step=3, health_flags=("explode",))
    assert RoundSignal.from_report(r).degraded is True
    r2 = StepReport(step=4, health_flags=())
    assert RoundSignal.from_report(r2).degraded is False
    r3 = StepReport(step=5)  # health pass off
    assert RoundSignal.from_report(r3).degraded is False


def test_classify_step_health_verdict():
    r = StepReport(step=1, wall_ms=10.0, compute_ms=8.0,
                   grad_norm=0.031, update_ratio_p95=2.1e-4,
                   nonfinite_leaves=0, health_flags=())
    msg = classify_step(r)
    assert "health: grad_norm 0.031" in msg
    assert "update p95" in msg
    r2 = StepReport(step=2, wall_ms=10.0, compute_ms=8.0,
                    grad_norm=0.03, nonfinite_leaves=3,
                    health_flags=("nonfinite",))
    msg2 = classify_step(r2)
    assert "HEALTH nonfinite" in msg2 and "3 nonfinite leaves" in msg2


def test_archive_record_gains_health_fields():
    from byteps_tpu.core.ledger import EfficiencyLedger
    r = StepReport(step=9, wall_ms=5.0, grad_norm=0.5,
                   update_ratio_p95=1e-3, nonfinite_leaves=0)
    rec = EfficiencyLedger._archive_record(r)
    assert rec["grad_norm"] == 0.5
    assert rec["update_ratio_p95"] == pytest.approx(1e-3)
    assert rec["nonfinite_leaves"] == 0


# --------------------------------------------------------------------- #
# perf-gate directionality (replay)
# --------------------------------------------------------------------- #


def _gate():
    spec = importlib.util.spec_from_file_location(
        "perf_gate_health", os.path.join(REPO, "ci", "perf_gate.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_perf_gate_health_directions():
    pg = _gate()
    assert pg.direction_for("grad_norm") is None
    assert pg.direction_for("update_ratio_p95") is None
    assert pg.direction_for("fidelity_drift") is None
    assert pg.direction_for("nonfinite_leaves") == "lower"
    assert pg.direction_for("health_overhead_pct") == "lower"
    assert pg.direction_for("health_on_step_ms") == "lower"


def test_perf_gate_health_replay():
    """A health-bearing archive never misreads as a perf regression:
    a wildly different grad_norm is skipped, while nonfinite_leaves
    growing from an all-zero history trips."""
    pg = _gate()
    baseline = {"keys": {
        "grad_norm": {"samples": [0.03, 0.031, 0.029]},
        "nonfinite_leaves": {"samples": [0, 0, 0]},
    }}
    rep = pg.compare({"grad_norm": 42.0, "nonfinite_leaves": 0},
                     baseline)
    verdicts = {e["key"]: e["verdict"] for e in rep["rows"]}
    assert verdicts["grad_norm"] == "skipped"
    assert verdicts["nonfinite_leaves"] == "pass"
    assert rep["ok"] is True
    rep2 = pg.compare({"grad_norm": 42.0, "nonfinite_leaves": 2},
                      baseline)
    verdicts2 = {e["key"]: e["verdict"] for e in rep2["rows"]}
    assert verdicts2["nonfinite_leaves"] == "regression"
    assert rep2["ok"] is False


# --------------------------------------------------------------------- #
# loopback PS end-to-end: fields, chaos, guard
# --------------------------------------------------------------------- #


@contextlib.contextmanager
def _ps_env(extra_env: dict = None):
    from byteps_tpu.core.state import GlobalState

    port = _PORT[0]
    _PORT[0] += 1
    env = {
        "DMLC_NUM_WORKER": "1", "DMLC_NUM_SERVER": "1",
        "DMLC_PS_ROOT_URI": "127.0.0.1", "DMLC_PS_ROOT_PORT": str(port),
        "BYTEPS_FORCE_DISTRIBUTED": "1", **(extra_env or {}),
    }
    saved = {k: os.environ.get(k) for k in env}
    os.environ.update(env)
    server = threading.Thread(
        target=run_server,
        args=(port, Config(num_workers=1, num_servers=1)), daemon=True)
    server.start()
    GlobalState._instance = None
    import byteps_tpu as bps
    bps.init()
    try:
        yield bps
    finally:
        bps.shutdown()
        server.join(timeout=10)
        GlobalState._instance = None
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def _train_rounds(steps=3, **kw):
    import jax
    import jax.numpy as jnp
    import optax

    from byteps_tpu.core.state import get_state
    from byteps_tpu.jax.train import make_ps_train_step
    from byteps_tpu.models import mlp

    cfg = mlp.MLPConfig(in_dim=64, hidden=(48, 32), n_classes=10)
    params = mlp.init_params(jax.random.PRNGKey(0), cfg)
    rng = np.random.RandomState(0)
    batch = {"x": jnp.asarray(rng.rand(32, 64), jnp.float32),
             "y": jnp.asarray(rng.randint(0, 10, 32), jnp.int32)}
    tx = optax.adam(1e-2)
    opt = tx.init(params)
    step = make_ps_train_step(lambda p, b: mlp.loss_fn(p, b, cfg), tx,
                              get_state().mesh, **kw)
    for _ in range(steps):
        params, opt, loss = step(params, opt, batch)
    return float(loss)


def test_loopback_health_end_to_end():
    """The acceptance run: BYTEPS_HEALTH=1 lands non-null grad_norm /
    update_ratio_p95, zero nonfinite leaves, a healthy () verdict, the
    health verdict in the diagnosis, live gauges, and nonzero in-fold
    stat slots on the server."""
    with _ps_env({"BYTEPS_HEALTH": "1"}) as bps:
        _train_rounds(steps=4)
        reports = bps.get_step_reports()
        assert len(reports) == 4
        last = reports[-1]
        assert last["grad_norm"] is not None and last["grad_norm"] > 0
        assert last["update_ratio_p95"] is not None
        assert last["update_ratio_p95"] > 0
        assert last["nonfinite_leaves"] == 0
        assert last["health_flags"] == ()
        m = bps.get_metrics()
        assert "health" in m["steps"]["last_diagnosis"]
        assert m["gauges"]["health/grad_norm"] == pytest.approx(
            last["grad_norm"])
        assert m["counters"]["health/nonfinite_rounds"] == 0
        # the native in-fold pass engaged: stat slots nonzero (fleet-
        # scoped: STATS_PULL against THIS run's server, immune to any
        # not-yet-reaped server from another test)
        fleet = m["fleet"]["server"]["0"]
        assert fleet["health_rounds"] > 0
        assert fleet["health_nonfinite"] == 0
        assert m["server"]["health_rounds"] >= fleet["health_rounds"]


def test_health_off_fields_none():
    with _ps_env() as bps:
        _train_rounds(steps=2)
        last = bps.get_step_reports()[-1]
        assert last["grad_norm"] is None
        assert last["nonfinite_leaves"] is None
        assert last["health_flags"] is None
        # fleet-scoped (STATS_PULL against THIS run's server): the
        # summed in-process `server` section could see another test's
        # not-yet-reaped server
        fleet = bps.get_metrics()["fleet"]["server"]["0"]
        assert fleet["health_rounds"] == 0


def test_chaos_nan_detect_flight_and_continue(tmp_path):
    """Guard OFF: the injected NaN is detected (nonfinite round +
    flight event, chaos-injection BEFORE detection in the causal
    record) and training CONTINUES — health/nonfinite_rounds counts."""
    with _ps_env({"BYTEPS_HEALTH": "1",
                  "BYTEPS_FUSION_BYTES": "0",
                  "BYTEPS_FLIGHT_DIR": str(tmp_path / "fl"),
                  "BYTEPS_CHAOS_NAN_LEAF": "grad/@2"}) as bps:
        _train_rounds(steps=5)  # no raise: guard off
        reports = bps.get_step_reports()
        assert len(reports) == 5
        assert any((r["nonfinite_leaves"] or 0) > 0 for r in reports)
        m = bps.get_metrics()
        assert m["counters"]["health/nonfinite_rounds"] >= 1
        # server side saw the poisoned aggregate too
        assert m["server"]["health_nonfinite"] >= 1
        from byteps_tpu.core import flight
        evs = flight.get_recorder().events()
        kinds = [e["kind"] for e in evs]
        assert "chaos_nan_injected" in kinds
        assert "health_nonfinite" in kinds
        # causality: injection recorded before detection
        assert kinds.index("chaos_nan_injected") \
            < kinds.index("health_nonfinite")


def test_chaos_nan_guard_failfast(tmp_path):
    """Guard ON: detect → flight events → bounded fail-fast naming the
    dumped flight record — never a silently poisoned run."""
    with _ps_env({"BYTEPS_HEALTH": "1", "BYTEPS_NAN_GUARD": "1",
                  "BYTEPS_FUSION_BYTES": "0",
                  "BYTEPS_FLIGHT_DIR": str(tmp_path / "fl"),
                  "BYTEPS_CHAOS_NAN_LEAF": "grad/@3"}) as bps:
        with pytest.raises(RuntimeError, match="BYTEPS_NAN_GUARD"):
            _train_rounds(steps=6)
        reports = bps.get_step_reports()
        assert any((r["nonfinite_leaves"] or 0) > 0 for r in reports)
        assert bps.get_metrics()["counters"][
            "health/nonfinite_rounds"] >= 1
        from byteps_tpu.core import flight
        kinds = [e["kind"] for e in flight.get_recorder().events()]
        assert "health_nonfinite" in kinds
    # the error names the dump and the dump exists
    dumps = list((tmp_path / "fl").glob("*.json"))
    assert dumps, "nan-guard did not dump a flight record"


def test_chaos_nan_guard_error_names_dump(tmp_path):
    """The raised error carries the _fatal_wire_error contract string
    (pinned separately so a reword can't silently drop the pointer)."""
    with _ps_env({"BYTEPS_HEALTH": "1", "BYTEPS_NAN_GUARD": "1",
                  "BYTEPS_FUSION_BYTES": "0",
                  "BYTEPS_FLIGHT_DIR": str(tmp_path / "fl"),
                  "BYTEPS_CHAOS_NAN_LEAF": "grad/@4"}):
        with pytest.raises(RuntimeError,
                           match="flight record dumped to"):
            _train_rounds(steps=7)
