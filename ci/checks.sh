#!/usr/bin/env bash
# Pre-PR gate (README.md "Before you send a PR"): the three checks a
# change must clear, in increasing cost order, with one summary at the
# end. Run from anywhere; the repo root is derived from this script.
#
#   1. byteps-lint   — static invariants (docs/static-analysis.md)
#   2. sanitize tier — TSAN/ASAN loopback stress incl. slow bursts
#                      (tests/test_sanitize.py)
#   3. tier-1        — the full non-slow test suite under the 870 s
#                      budget (ROADMAP.md "Tier-1 verify")
#
# Every stage runs even if an earlier one fails (a PR author wants the
# whole picture in one pass); the exit code is nonzero if ANY failed.

set -u
ROOT="$(cd "$(dirname "${BASH_SOURCE[0]}")/.." && pwd)"
cd "$ROOT"

declare -a NAMES=() RESULTS=()
overall=0

run_stage() {
  local name="$1"; shift
  echo
  echo "=== [$name] $*"
  local t0=$SECONDS
  if "$@"; then
    RESULTS+=("PASS $((SECONDS - t0))s")
  else
    RESULTS+=("FAIL $((SECONDS - t0))s")
    overall=1
  fi
  NAMES+=("$name")
}

run_stage "byteps-lint" python -m byteps_tpu.tools.lint

# byteps-top CI smoke: one --once frame over a synthetic timeseries
# JSONL artifact must print schema byteps-top/1 with live series — the
# console's whole read path (artifact detect → rehydrate → frame)
run_stage "top-smoke" env JAX_PLATFORMS=cpu python - <<'PY'
import json, os, subprocess, sys, tempfile
art = os.path.join(tempfile.mkdtemp(prefix="bps-top-smoke-"),
                   "timeseries-1.jsonl")
with open(art, "w") as f:
    f.write(json.dumps({"kind": "timeseries", "reason": "smoke",
                        "pid": 1, "points": 512, "steps": 3,
                        "series_count": 1, "dropped_series": 0}) + "\n")
    f.write(json.dumps({"name": "step/wall_ms", "steps": [1, 2, 3],
                        "values": [10.0, 11.0, 9.5]}) + "\n")
out = subprocess.run(
    [sys.executable, "-m", "byteps_tpu.tools.top", "--once",
     "--file", art], capture_output=True, text=True, timeout=120)
frame = json.loads(out.stdout)
assert out.returncode == 0, out.stderr
assert frame["schema"] == "byteps-top/1", frame
assert frame["series"]["step/wall_ms"]["points"] == 3, frame
print("[top-smoke] ok:", json.dumps(frame)[:120], "...")
PY

# advisory (never fails the gate): curated clang-tidy over ps.cc when
# the tool is installed — this is the ONLY place it runs, so the lazy
# import-time native build stays a pure -Werror compile
python - <<'PY'
from byteps_tpu.native.build import clang_tidy
import shutil
if shutil.which("clang-tidy") is None:
    print("[clang-tidy] not installed; skipping (advisory)")
else:
    report = clang_tidy()
    print(report if report else "[clang-tidy] clean")
PY

# advisory (never fails the gate): noise-aware perf regression check of
# the newest parsed driver artifact against the committed baseline —
# the sample histories in ci/perf_baseline.json define the noise band
# (ci/perf_gate.py; docs/performance.md "Perf regression gate")
candidate=$(ls "$ROOT"/BENCH_r*.json 2>/dev/null | sort | tail -1)
echo
if [ -n "$candidate" ]; then
  echo "=== [perf-gate] advisory: $(basename "$candidate") vs ci/perf_baseline.json"
  python ci/perf_gate.py --baseline ci/perf_baseline.json \
    --candidate "$candidate"
  case $? in
    0) ;;
    1) echo "[perf-gate] regression flagged (advisory — does not fail the gate)" ;;
    *) echo "[perf-gate] gate did not run (bad baseline/candidate; advisory)" ;;
  esac
else
  echo "=== [perf-gate] no BENCH_r*.json candidate; skipping (advisory)"
fi

# slow markers included: the sanitize tier IS the slow TSAN/ASAN burst
# plus the fast Waiter-pool smoke; it builds its own instrumented libs
run_stage "sanitize" env JAX_PLATFORMS=cpu \
  python -m pytest tests/test_sanitize.py -q -m '' \
  -p no:cacheprovider

# --ignore=test_sanitize.py: stage 2 is authoritative for that file;
# without it tier-1 would re-run the non-slow TSAN smoke it contains
run_stage "tier-1" bash -c "
  set -o pipefail
  timeout -k 10 870 env JAX_PLATFORMS=cpu \
    python -m pytest tests/ -q -m 'not slow' \
    --ignore=tests/test_sanitize.py \
    --continue-on-collection-errors -p no:cacheprovider \
    -p no:xdist -p no:randomly"

echo
echo "=== pre-PR gate summary"
for i in "${!NAMES[@]}"; do
  printf '  %-12s %s\n' "${NAMES[$i]}" "${RESULTS[$i]}"
done
if [ "$overall" -eq 0 ]; then
  echo "  ALL CHECKS PASSED"
else
  echo "  GATE FAILED"
fi
exit "$overall"
