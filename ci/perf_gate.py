#!/usr/bin/env python
"""Noise-aware perf regression gate — the first machine check that a
PR didn't quietly give back a measured win (PR 11's 1.9 GB/s class).

Compares a candidate — a driver artifact (``BENCH_rNN.json``), a raw
``bench.py`` result line, or a step-ledger perf archive
(``perf-*.jsonl``, ``BYTEPS_PERF_ARCHIVE``) — against a committed
baseline (``ci/perf_baseline.json``) whose per-key SAMPLE LISTS carry
the run-to-run history. The statistics are deliberately robust:

- center   = median of the baseline samples (median-of-reps: a
  candidate list of reps is collapsed to ITS median too);
- spread   = MAD scaled to sigma (1.4826 x median absolute deviation)
  — the history IS the noise model, so a key that historically swings
  26 % between rounds (loopback GB/s on a shared 1-core host does)
  needs a far bigger drop to trip than a tight one;
- verdict  = regression iff the candidate is WORSE than the center by
  more than ``max(rel_floor x |center|, noise_k x sigma)`` in that
  key's bad direction — per-key directionality ("gbps up" and
  "step_ms down" are both wins) from an explicit table plus suffix
  rules; keys with no known direction are skipped, never guessed.

A null/missing candidate value reads as ``missing`` (a wedged round
must not be reported as a perf loss), and improvements past the same
threshold are reported symmetrically.

Wired into ``ci/checks.sh`` as an ADVISORY stage (prints, never fails
the pre-PR gate) and into ``bench.py --baseline`` (verdict rides the
result JSON as ``perf_gate``). Stdlib-only by contract: the bench
parent process never imports jax, and neither may this.

Usage:
    python ci/perf_gate.py --baseline ci/perf_baseline.json \\
        --candidate BENCH_r05.json [--rel-floor 0.10] [--noise-k 3.0]

Exit codes: 0 = no regressions, 1 = regression(s), 2 = usage error.
"""

from __future__ import annotations

import json
import sys
from typing import List, Optional

# Keys whose better-direction a suffix rule would get wrong (or miss).
DIRECTION_OVERRIDES = {
    "value": "higher",                    # tokens/s headline
    "vs_baseline": "higher",
    "mfu": "higher",
    "scaling_efficiency_2w": "higher",
    "scaling_vs_core_cap": "higher",
    "wire_request_ratio": "lower",        # fused/two-op message ratio
    "scaleup_ratio": "lower",             # after/before step wall
    "shard_reduction_ratio": "higher",    # whole-leaf/shard bytes
    "codec_adapt_wire_reduction": "lower",  # adaptive/dense wire bytes
    "overlap_frac": "higher",
    "wire_efficiency": "higher",
    "ledger_mfu": "higher",
    "ledger_overlap_frac": "higher",
    "ledger_wire_efficiency": "higher",
    "achieved_flops": "higher",
    "wire_bytes": "lower",
    # training-health archive keys (core/health.py): a gradient norm
    # has NO better-direction — an explicit None pins it skipped so a
    # future suffix rule can never misread a healthy optimization
    # change as a perf regression; update_ratio_p95 likewise (and its
    # _efficiency-adjacent spelling must not hit a suffix rule).
    # nonfinite_leaves IS directional: any growth is a poisoned run.
    "grad_norm": None,
    "update_ratio_p95": None,
    "fidelity_drift": None,
    "nonfinite_leaves": "lower",
    # cross-barrier pipelining (bench.py barrier_ab): the step-wall and
    # overlap keys ride the suffix rules (_step_ms lower, _frac
    # higher); the engaged-proof counters are directional — a drop to
    # zero means the carry silently disengaged (the win evaporates),
    # and the sync arm carrying ANYTHING is a staleness-0 contract
    # violation.
    "barrier_speedup": "higher",
    "barrier_carried_leaves": "higher",
    "barrier_carry_drained": "higher",
    "barrier_sync_carried_leaves": "lower",
    # cross-host wire plane (bench.py stripe_ab): the five *_gbps keys
    # ride the suffix rule; the ratios and engaged-proof counters are
    # directional — stripe_ab_segs dropping to zero means the striper
    # silently disengaged, msgs_per_batch falling to 1.0 means the
    # reply ring stopped coalescing (the syscall win evaporates), and
    # lossless_gain under 1.0 means decompress-on-the-fabric no longer
    # beats raw bytes under the same wire cap.
    "stripe_ab_speedup": "higher",
    "stripe_ab_segs": "higher",
    "stripe_ab_msgs_per_batch": "higher",
    "stripe_ab_lossless_gain": "higher",
}
# (suffix, direction) checked in order after the overrides; the first
# match wins. "_ms" covers every step-wall key; "_pct" the overhead
# A/Bs; throughput families end in _gbps / tokens_per_sec.
SUFFIX_RULES = (
    ("_gbps", "higher"),
    ("_tokens_per_sec", "higher"),
    ("_step_ms", "lower"),
    ("_ms", "lower"),
    ("_overhead_pct", "lower"),
    ("_frac", "higher"),
    ("_efficiency", "higher"),
)


def direction_for(key: str) -> Optional[str]:
    """"higher" / "lower" = which way is better; None = unknown (the
    key is skipped — a guessed direction would flag wins as losses)."""
    if key in DIRECTION_OVERRIDES:
        return DIRECTION_OVERRIDES[key]
    if key.startswith("tokens_per_sec"):
        return "higher"
    for suffix, d in SUFFIX_RULES:
        if key.endswith(suffix):
            return d
    return None


def median(xs: List[float]) -> float:
    s = sorted(xs)
    n = len(s)
    mid = n // 2
    return s[mid] if n % 2 else (s[mid - 1] + s[mid]) / 2.0


def mad(xs: List[float]) -> float:
    """Median absolute deviation (unscaled)."""
    m = median(xs)
    return median([abs(x - m) for x in xs])


def load_baseline(path: str) -> dict:
    with open(path) as f:
        doc = json.load(f)
    if not isinstance(doc, dict) or "keys" not in doc:
        raise ValueError(f"{path}: not a perf baseline (no 'keys')")
    return doc


def load_candidate(path: str) -> dict:
    """Candidate metrics from any of the three shapes:

    - ``*.jsonl`` — a step-ledger perf archive: each numeric key
      collapses to the median over its records (median-of-steps);
    - a driver artifact — ``{"parsed": {...}}`` wrapper: the parsed
      result (a null parse yields an empty candidate — every key then
      reads ``missing``, never ``regression``);
    - a raw bench result line / arbitrary flat JSON dict.
    """
    if path.endswith(".jsonl"):
        per_key: dict = {}
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                rec = json.loads(line)
                for k, v in rec.items():
                    if isinstance(v, (int, float)) \
                            and not isinstance(v, bool):
                        per_key.setdefault(k, []).append(float(v))
        return {k: median(vs) for k, vs in per_key.items()}
    with open(path) as f:
        doc = json.load(f)
    if isinstance(doc, dict) and "parsed" in doc:
        doc = doc["parsed"] or {}
    if not isinstance(doc, dict):
        raise ValueError(f"{path}: candidate is not a JSON object")
    return doc


def compare(candidate: dict, baseline: dict, rel_floor: float = 0.10,
            noise_k: float = 3.0) -> dict:
    """Per-key verdicts for every baseline key. A key regresses iff
    its candidate value is worse than the baseline median by more than
    ``max(rel_floor x |median|, noise_k x 1.4826 x MAD)``."""
    rows = []
    for key, spec in sorted(baseline.get("keys", {}).items()):
        samples = [float(s) for s in spec.get("samples", [])
                   if isinstance(s, (int, float))
                   and not isinstance(s, bool)]
        if not samples:
            continue
        d = spec.get("direction") or direction_for(key)
        if d not in ("higher", "lower"):
            rows.append({"key": key, "verdict": "skipped",
                         "reason": "unknown direction"})
            continue
        v = candidate.get(key)
        if isinstance(v, list):
            vs = [float(x) for x in v
                  if isinstance(x, (int, float))
                  and not isinstance(x, bool)]
            v = median(vs) if vs else None
        if not isinstance(v, (int, float)) or isinstance(v, bool):
            rows.append({"key": key, "verdict": "missing"})
            continue
        center = median(samples)
        sigma = 1.4826 * mad(samples)
        threshold = max(rel_floor * abs(center), noise_k * sigma)
        delta = (center - v) if d == "higher" else (v - center)
        if delta > threshold:
            verdict = "regression"
        elif -delta > threshold:
            verdict = "improvement"
        else:
            verdict = "pass"
        rows.append({"key": key, "verdict": verdict,
                     "value": float(v), "median": center,
                     "sigma": round(sigma, 6),
                     "threshold": round(threshold, 6),
                     "direction": d, "n_samples": len(samples)})
    regressions = [r for r in rows if r["verdict"] == "regression"]
    return {"rows": rows, "regressions": regressions,
            "ok": not regressions,
            "checked": sum(1 for r in rows
                           if r["verdict"] in ("pass", "regression",
                                               "improvement")),
            "rel_floor": rel_floor, "noise_k": noise_k}


def summarize(report: dict) -> dict:
    """Compact form for embedding in a bench result line."""
    return {
        "ok": report["ok"],
        "checked": report["checked"],
        "regressions": [
            {"key": r["key"], "value": r["value"],
             "median": r["median"], "threshold": r["threshold"]}
            for r in report["regressions"]],
        "improvements": [r["key"] for r in report["rows"]
                         if r["verdict"] == "improvement"],
        "missing": [r["key"] for r in report["rows"]
                    if r["verdict"] == "missing"],
    }


def format_report(report: dict) -> str:
    lines = []
    for r in report["rows"]:
        if r["verdict"] in ("skipped", "missing"):
            lines.append(f"  [{r['verdict']:>11}] {r['key']}")
            continue
        lines.append(
            f"  [{r['verdict']:>11}] {r['key']}: {r['value']:g} vs "
            f"median {r['median']:g} "
            f"(threshold {r['threshold']:g}, {r['direction']} is "
            f"better, n={r['n_samples']})")
    verdict = "OK" if report["ok"] else \
        f"{len(report['regressions'])} REGRESSION(S)"
    lines.append(f"perf-gate: {verdict} "
                 f"({report['checked']} key(s) checked, "
                 f"rel_floor={report['rel_floor']:g}, "
                 f"noise_k={report['noise_k']:g})")
    return "\n".join(lines)


def main(argv: List[str]) -> int:
    args = {}
    flags = {"--baseline": "baseline", "--candidate": "candidate",
             "--rel-floor": "rel_floor", "--noise-k": "noise_k"}
    i = 0
    while i < len(argv):
        if argv[i] in flags and i + 1 < len(argv):
            args[flags[argv[i]]] = argv[i + 1]
            i += 2
            continue
        sys.stderr.write(f"perf_gate: unknown/incomplete arg "
                         f"{argv[i]!r}\n{__doc__.splitlines()[0]}\n")
        return 2
    if "baseline" not in args or "candidate" not in args:
        sys.stderr.write(
            "usage: perf_gate.py --baseline FILE --candidate FILE "
            "[--rel-floor F] [--noise-k K]\n")
        return 2
    try:
        baseline = load_baseline(args["baseline"])
        candidate = load_candidate(args["candidate"])
    except (OSError, ValueError, json.JSONDecodeError) as e:
        sys.stderr.write(f"perf_gate: {e}\n")
        return 2
    report = compare(candidate, baseline,
                     rel_floor=float(args.get("rel_floor", 0.10)),
                     noise_k=float(args.get("noise_k", 3.0)))
    print(format_report(report))
    return 0 if report["ok"] else 1


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
